"""Fused RNN layers (reference: python/mxnet/gluon/rnn/rnn_layer.py ~L1-600:
RNN/LSTM/GRU dispatching to the fused `RNN` op with cuDNN/MIOpen backend).

Here the fused backend is the lax.scan op `_fused_rnn` (ops/rnn_ops.py).
Parameter naming matches the reference ({l,r}{i}_{i2h,h2h}_{weight,bias})
so checkpoints map 1:1.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC', 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in (["l", "r"] if bidirectional else ["l"]):
                    name = f"{j}{i}_i2h_weight"
                    setattr(self, name, self.params.get(
                        name, shape=(ng * nh, ni), allow_deferred_init=True,
                        init=i2h_weight_initializer))
                    name = f"{j}{i}_h2h_weight"
                    setattr(self, name, self.params.get(
                        name, shape=(ng * nh, nh), allow_deferred_init=True,
                        init=h2h_weight_initializer))
                    name = f"{j}{i}_i2h_bias"
                    setattr(self, name, self.params.get(
                        name, shape=(ng * nh,), allow_deferred_init=True,
                        init=i2h_bias_initializer))
                    name = f"{j}{i}_h2h_bias"
                    setattr(self, name, self.params.get(
                        name, shape=(ng * nh,), allow_deferred_init=True,
                        init=h2h_bias_initializer))
                ni = nh * self._dir

    def _alias(self):
        # called during __init__ before _mode is set; fall back to class name
        return getattr(self, "_mode", type(self).__name__.lower())

    def state_info(self, batch_size=0):
        if self._mode == "lstm":
            return [
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
            ]
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **{**info, **kwargs}))
        return states

    def infer_shape(self, x, *args):
        ni = int(x.shape[-1])  # feature dim is the last axis in TNC and NTC
        ng, nh = self._gates, self._hidden_size
        layer_input = ni
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                getattr(self, f"{j}{i}_i2h_weight")._set_shape_if_deferred(
                    (ng * nh, layer_input))
            layer_input = nh * self._dir

    def __call__(self, inputs, states=None, **kwargs):
        # The traced function ALWAYS returns (out, state_list) so the CachedOp
        # output structure is independent of how the user called us; unwrap
        # here when states were omitted.
        skip_states = states is None
        if states is None:
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch, ctx=inputs.context,
                                      dtype=inputs.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        out = super().__call__(inputs, list(states), **kwargs)
        if skip_states:
            return out[0]
        return out

    def forward(self, x, states):
        ctx = x.context
        from ..parameter import DeferredInitializationError

        try:
            params = {name: p.data(ctx) for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, states)
            for p in self._reg_params.values():
                if p._deferred is not None:
                    p._finish_deferred_init()
            params = {name: p.data(ctx) for name, p in self._reg_params.items()}
        from ... import ndarray as F

        return self.hybrid_forward(F, x, states, **params)

    def hybrid_forward(self, F, inputs, states, **params):
        from ... import autograd
        from ... import random as _rng
        from ...ndarray import NDArray
        from ...ops import registry as _reg

        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        weights = []
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                weights.extend([
                    params[f"{j}{i}_i2h_weight"],
                    params[f"{j}{i}_h2h_weight"],
                    params[f"{j}{i}_i2h_bias"],
                    params[f"{j}{i}_h2h_bias"],
                ])
        state_h = states[0]
        state_c = states[1] if self._mode == "lstm" else F.zeros_like(states[0])
        key = NDArray(_rng.next_key(), ctx=inputs.context)
        outs = _reg.invoke_by_name(
            "_fused_rnn", [inputs, key, state_h, state_c] + weights,
            mode=self._mode, state_size=self._hidden_size,
            num_layers=self._num_layers, bidirectional=self._dir == 2,
            p=self._dropout, training=autograd.is_training())
        out = outs[0]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        return out, list(outs[1:])


class RNN(_RNNLayer):
    """Vanilla RNN layer (relu/tanh) — reference rnn_layer.py RNN."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation,
                         prefix=prefix, params=params)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", prefix=prefix,
                         params=params)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", prefix=prefix,
                         params=params)
