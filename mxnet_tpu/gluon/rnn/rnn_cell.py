"""Recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py ~L1-1000).

Cells are HybridBlocks stepping one timestep; unroll() builds the python
loop which, under hybridize, is flattened into the traced jaxpr (XLA then
optimizes the unrolled graph).  The fused multi-step path is rnn_layer.py
(lax.scan-based _fused_rnn op), matching the reference's cell/fused split.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    from ... import ndarray as nd
    from ...ndarray import NDArray

    assert layout in ("NTC", "TNC")
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[axis]
            inputs = nd.split(inputs, num_outputs=inputs.shape[axis],
                              axis=axis, squeeze_axis=True)
            if not isinstance(inputs, list):
                inputs = [inputs]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = nd.stack(*inputs, axis=axis)
    return inputs, axis, batch_size


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, list):
        outputs = F.SequenceMask(data, sequence_length=valid_length,
                                 use_sequence_length=True, axis=time_axis)
    else:
        outputs = F.SequenceMask(F.stack(*data, axis=time_axis),
                                 sequence_length=valid_length,
                                 use_sequence_length=True, axis=time_axis)
        if not merge:
            outputs = F.split(outputs, num_outputs=len(data), axis=time_axis,
                              squeeze_axis=True)
    return outputs


class RecurrentCell(Block):
    """Base recurrent cell (reference ~L80)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            state = func(shape=shape, **{**info, **kwargs})
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size, ctx=inputs[0].context,
                             dtype=inputs[0].dtype)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [
                F.SequenceLast(F.stack(*ele_list, axis=0),
                               sequence_length=valid_length,
                               use_sequence_length=True, axis=0)
                for ele_list in zip(*all_states)
            ]
            outputs = _mask_sequence_variable_length(F, outputs, length,
                                                     valid_length, axis, True)
            if merge_outputs is False:
                outputs = F.split(outputs, num_outputs=length, axis=axis,
                                  squeeze_axis=True)
        elif merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)

    def __call__(self, inputs, states):
        self._counter += 1
        return HybridBlock.__call__(self, inputs, states)

    def forward(self, x, states):
        ctx = x.context
        from ..parameter import DeferredInitializationError

        try:
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, states)
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        from ... import ndarray as F

        return self.hybrid_forward(F, x, states, **params)

    def hybrid_forward(self, F, x, states, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape_if_deferred(
            (self._hidden_size, int(x.shape[-1])))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gate order [i, f, c, o] (reference ~L400)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape_if_deferred(
            (4 * self._hidden_size, int(x.shape[-1])))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=1)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2], self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, gate order [r, z, n] (cuDNN/reference convention)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape_if_deferred(
            (3 * self._hidden_size, int(x.shape[-1])))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in sequence each step (reference ~L700)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            inputs, state = cell(inputs, states[p: p + n])
            p += n
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, *args):
        raise NotImplementedError


class _ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell cannot be modified twice"
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ZoneoutCell(_ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        from ... import autograd

        if not autograd.is_training():
            return next_output, next_states

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        p_outputs = self.zoneout_outputs
        p_states = self.zoneout_states
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(_ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def _alias(self):
        return "bi"

    def __call__(self, inputs, states):
        raise MXNetError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size, ctx=inputs[0].context,
                             dtype=inputs[0].dtype)
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        reversed_inputs = list(reversed(inputs))
        if valid_length is not None:
            reversed_inputs = F.SequenceReverse(
                F.stack(*inputs, axis=0), sequence_length=valid_length,
                use_sequence_length=True, axis=0)
            reversed_inputs = F.split(reversed_inputs, num_outputs=length,
                                      axis=0, squeeze_axis=True)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs, begin_state=states[n_l:],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            stacked = F.stack(*r_outputs, axis=0)
            rev = F.SequenceReverse(stacked, sequence_length=valid_length,
                                    use_sequence_length=True, axis=0)
            r_outputs = F.split(rev, num_outputs=length, axis=0,
                                squeeze_axis=True)
        else:
            r_outputs = list(reversed(r_outputs))
        if merge_outputs and not isinstance(l_outputs, list):
            l_list = F.split(l_outputs, num_outputs=length, axis=axis,
                             squeeze_axis=True)
        else:
            l_list = l_outputs
        outputs = [F.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_list, r_outputs)]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
