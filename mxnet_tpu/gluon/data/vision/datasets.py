"""Vision datasets (reference: gluon/data/vision/datasets.py ~L1-400).

Zero-egress environment: datasets read from local files only (standard
IDX/pickle formats); if files are absent a deterministic synthetic fallback
with the same shapes/dtypes is generated so training scripts and tests run
anywhere.  The download(...) helpers of the reference are intentionally not
reproduced.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ..dataset import ArrayDataset, Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._transform = transform
        self._train = train
        self._root = os.path.expanduser(root)
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        from .... import ndarray as nd

        x = nd.array(self._data[idx], dtype=self._data.dtype)
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _synthetic(num, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    data = (rng.rand(num, *shape) * 255).astype(np.uint8)
    label = rng.randint(0, num_classes, num).astype(np.int32)
    return data, label


class MNIST(_DownloadedDataset):
    """MNIST from local IDX files, or synthetic fallback (28x28x1 uint8)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train_data = ("train-images-idx3-ubyte.gz",
                            "train-labels-idx1-ubyte.gz")
        self._test_data = ("t10k-images-idx3-ubyte.gz",
                           "t10k-labels-idx1-ubyte.gz")
        self._num_synthetic = 2048
        super().__init__(root, train, transform)

    def _get_data(self):
        images, labels = self._train_data if self._train else self._test_data
        image_path = os.path.join(self._root, images)
        label_path = os.path.join(self._root, labels)
        if os.path.exists(image_path) and os.path.exists(label_path):
            with gzip.open(label_path, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
            with gzip.open(image_path, "rb") as fin:
                _, num, rows, cols = struct.unpack(">IIII", fin.read(16))
                data = np.frombuffer(fin.read(), dtype=np.uint8)
                data = data.reshape(num, rows, cols, 1)
        else:
            data, label = _synthetic(self._num_synthetic, (28, 28, 1), 10,
                                     seed=42 if self._train else 43)
        self._data = data
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from local binary batches, or synthetic fallback (32x32x3)."""

    _num_classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._num_synthetic = 2048
        super().__init__(root, train, transform)

    def _get_data(self):
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if self._train
                 else ["test_batch.bin"])
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            data_list, label_list = [], []
            row = 1 + 32 * 32 * 3 if self._num_classes == 10 else 2 + 32 * 32 * 3
            for path in paths:
                raw = np.fromfile(path, dtype=np.uint8).reshape(-1, row)
                label_list.append(raw[:, row - 3073].astype(np.int32))
                imgs = raw[:, row - 3072:].reshape(-1, 3, 32, 32)
                data_list.append(imgs.transpose(0, 2, 3, 1))
            self._data = np.concatenate(data_list)
            self._label = np.concatenate(label_list)
        else:
            self._data, self._label = _synthetic(
                self._num_synthetic, (32, 32, 3), self._num_classes,
                seed=44 if self._train else 45)


class CIFAR100(CIFAR10):
    _num_classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        super().__init__(root, train, transform)


class ImageRecordDataset(Dataset):
    """Dataset over packed image RecordIO (reference: ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import image, recordio

        raw = self._record[idx]
        header, img = recordio.unpack(raw)
        x = image.imdecode(img, self._flag)
        y = header.label
        if self._transform is not None:
            return self._transform(x, y)
        return x, y
