"""Vision transforms (reference: gluon/data/vision/transforms.py ~L1-500,
backed by src/operator/image/ ops).  Transforms are HybridBlocks operating
on HWC uint8/float images, like the reference.
"""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ...nn.basic_layers import HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation"]


class Compose(HybridSequential):
    def __init__(self, transforms):
        super().__init__()
        with self.name_scope():
            for t in transforms:
                self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: image.to_tensor)."""

    def hybrid_forward(self, F, x):
        x = F.Cast(x, dtype="float32") / 255.0
        if x.ndim == 4:
            return x.transpose((0, 3, 1, 2))
        return x.transpose((2, 0, 1))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        from .... import ndarray as nd

        mean = nd.array(self._mean, ctx=x.context)
        std = nd.array(self._std, ctx=x.context)
        return (x - mean) / std


class Resize(HybridBlock):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def hybrid_forward(self, F, x):
        import jax.image

        from ....ops import registry as _reg

        w, h = self._size

        def fn(img):
            if img.ndim == 3:
                return jax.image.resize(
                    img.astype("float32"), (h, w, img.shape[2]),
                    method="bilinear").astype(img.dtype)
            return jax.image.resize(
                img.astype("float32"), (img.shape[0], h, w, img.shape[3]),
                method="bilinear").astype(img.dtype)

        return _reg.invoke_fn(fn, [x])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    """Random area/aspect crop + resize (reference: transforms ~L300)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import jax.image

        from ....ops import registry as _reg

        H, W = int(x.shape[-3]), int(x.shape[-2])
        area = H * W
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            w = int(round(np.sqrt(target_area * aspect)))
            h = int(round(np.sqrt(target_area / aspect)))
            if 0 < w <= W and 0 < h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                break
        else:
            crop = x
        tw, th = self._size

        def fn(img):
            return jax.image.resize(
                img.astype("float32"), (th, tw, img.shape[-1]),
                method="bilinear").astype(img.dtype)

        return _reg.invoke_fn(fn, [crop])


class _RandomApply(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return self._apply(x)
        return x


class RandomFlipLeftRight(_RandomApply):
    def _apply(self, x):
        return x[..., :, ::-1, :]


class RandomFlipTopBottom(_RandomApply):
    def _apply(self, x):
        return x[..., ::-1, :, :]


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._b, self._b)
        return x * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._c, self._c)
        gray = x.mean()
        return x * alpha + gray * (1 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        from .... import ndarray as nd

        alpha = 1.0 + np.random.uniform(-self._s, self._s)
        coef = nd.array(np.array([0.299, 0.587, 0.114], np.float32), ctx=x.context)
        gray = (x * coef.reshape(1, 1, 3)).sum(axis=-1, keepdims=True)
        return x * alpha + gray * (1 - alpha)
