"""Vision datasets and transforms (reference: gluon/data/vision/)."""
from . import transforms
from .datasets import MNIST, FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset
