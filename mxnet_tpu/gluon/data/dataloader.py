"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py ~L400).

The reference uses multiprocessing workers passing NDArrays through POSIX
shared memory (cpu_shared_storage_manager.h).  This rebuild keeps both
transports:

- ``num_workers>0`` (default): PROCESS workers — batches cross back via
  ``multiprocessing.shared_memory`` (one copy into shm in the worker, one
  device_put out of it in the parent), matching the reference's shm
  design.  This is the path for GIL-bound python transforms.  Workers use
  the ``spawn`` start method (an initialized PjRt client does not survive
  fork) and pin themselves to the CPU backend — the input pipeline is
  host work by definition.  Dataset + batchify_fn must be picklable,
  and (standard ``spawn`` rule) a script creating a worker-backed
  DataLoader at module level needs an ``if __name__ == "__main__"``
  guard — children re-import ``__main__``.
- ``thread_pool=True``: the round-3 thread pool — zero transport cost,
  right when the heavy lifting already releases the GIL (libmxio, numpy).

``pin_memory`` is accepted and ignored: jax.device_put is the only
host->device path on TPU and stages through PjRt's own pinned buffers.
"""
from __future__ import annotations

import multiprocessing
import pickle
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

import numpy as np

from ...base import MXNetError
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]

# arrays at/above this size ride shared memory; smaller ones pickle
_SHM_MIN_BYTES = 1 << 15

_worker_state = None  # (dataset, batchify_fn) inside a worker process


def _worker_init(payload: bytes):
    # FIRST: pin the worker to the host backend.  The spawned child
    # inherits JAX_PLATFORMS=axon-style env; a worker must never try to
    # claim (or hang on) the accelerator relay.
    import jax

    jax.config.update("jax_platforms", "cpu")
    global _worker_state
    _worker_state = pickle.loads(payload)


def _encode(obj, created=None):
    """Worker-side: batch pytree -> picklable tree with big ndarrays in
    POSIX shared memory (reference: cpu_shared storage, ~L60).  `created`
    collects segment names so a mid-batch failure (e.g. ENOSPC on the
    second array) can unlink what the batch already allocated."""
    from ...ndarray import NDArray

    if isinstance(obj, NDArray):
        obj = obj.asnumpy()
    if isinstance(obj, np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        if created is not None:
            created.append(shm.name)
        np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
        name = shm.name
        shm.close()
        return ("shm", name, obj.shape, str(obj.dtype))
    if isinstance(obj, (list, tuple)):
        return ("seq", isinstance(obj, tuple),
                [_encode(o, created) for o in obj])
    return ("raw", obj)


def _decode(enc):
    """Parent-side: rebuild the batch; shm segments are copied into device
    buffers (nd.array) and unlinked immediately."""
    from ... import ndarray as nd

    kind = enc[0]
    if kind == "shm":
        from multiprocessing import shared_memory

        _, name, shape, dtype = enc
        shm = shared_memory.SharedMemory(name=name)
        try:
            # explicit heap copy BEFORE unlink: the CPU backend's
            # device_put aliases host numpy memory zero-copy, so handing
            # the shm view to nd.array and unmapping would leave the
            # device buffer pointing at freed pages
            arr = np.ndarray(shape, dtype, buffer=shm.buf).copy()
        finally:
            shm.close()
            shm.unlink()
        return nd.array(arr, dtype=arr.dtype)
    if kind == "seq":
        _, is_tuple, items = enc
        vals = [_decode(o) for o in items]
        return tuple(vals) if is_tuple else vals
    val = enc[1]
    if isinstance(val, np.ndarray):
        return nd.array(val, dtype=val.dtype)
    return val


def _free(enc):
    """Unlink an encoded batch's shm segments without decoding it."""
    if enc[0] == "shm":
        _unlink([enc[1]])
    elif enc[0] == "seq":
        for o in enc[2]:
            _free(o)


def _unlink(names):
    from multiprocessing import shared_memory

    for name in names:
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass


def _worker_fn(indices):
    dataset, batchify_fn = _worker_state
    created = []
    try:
        return _encode(batchify_fn([dataset[i] for i in indices]), created)
    except BaseException:
        _unlink(created)  # don't leak this batch's finished segments
        raise


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py ~L130)."""
    from ... import ndarray as nd
    from ...ndarray import NDArray

    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = np.asarray(data)
    return nd.array(arr, dtype=arr.dtype)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler=None, last_batch=None,
                 batch_sampler=None, batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 pin_device_id: int = 0, prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120,
                 prefetch_to=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = (RandomSampler(len(dataset)) if shuffle
                           else SequentialSampler(len(dataset)))
            elif shuffle:
                raise MXNetError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._timeout = timeout
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        # device-side prefetch hook (docs/PERFORMANCE.md §Async pipeline):
        # a DataParallelStep here stages every yielded batch onto the
        # step's input shardings in a background thread, so step() skips
        # its own H2D transfer
        self._prefetch_to = prefetch_to
        self._pool = None  # lazy persistent process pool

    def _load(self, indices) -> object:
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _get_pool(self):
        if self._pool is None:
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
            payload = pickle.dumps((self._dataset, self._batchify_fn))
            self._pool = ctx.Pool(self._num_workers, initializer=_worker_init,
                                  initargs=(payload,))
        return self._pool

    def __iter__(self):
        if self._prefetch_to is None:
            return self._iter_batches()
        from ...io.io import stage_batches

        return stage_batches(self._iter_batches(), self._prefetch_to)

    def _iter_batches(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._load(batch)
            return
        if self._thread_pool:
            # thread pool with bounded prefetch (double buffering)
            with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
                batches = iter(self._batch_sampler)
                futures = []
                try:
                    for _ in range(self._prefetch or self._num_workers):
                        futures.append(pool.submit(self._load, next(batches)))
                except StopIteration:
                    pass
                while futures:
                    fut = futures.pop(0)
                    try:
                        futures.append(pool.submit(self._load, next(batches)))
                    except StopIteration:
                        pass
                    yield fut.result()
            return
        # process workers + shared-memory transport (reference semantics)
        pool = self._get_pool()
        batches = iter(self._batch_sampler)
        pending = []
        try:
            try:
                for _ in range(self._prefetch or self._num_workers):
                    pending.append(
                        pool.apply_async(_worker_fn, (next(batches),)))
            except StopIteration:
                pass
            while pending:
                res = pending.pop(0)
                try:
                    pending.append(
                        pool.apply_async(_worker_fn, (next(batches),)))
                except StopIteration:
                    pass
                yield _decode(res.get(self._timeout))
        finally:
            # abandoned iteration (break/exception): prefetched batches
            # hold live /dev/shm segments — drain and unlink them or they
            # accumulate until ENOSPC.  A worker still stuck past two
            # timeouts is best-effort: warn with the leak's identity
            # instead of silently dropping it.
            for res in pending:
                for attempt in (1, 2):
                    try:
                        _free(res.get(self._timeout))
                        break
                    except multiprocessing.TimeoutError:
                        if attempt == 2:
                            import warnings

                            warnings.warn(
                                "DataLoader drain timed out; a prefetched "
                                "batch's shared-memory segments may leak "
                                "until process exit")
                    except Exception:
                        break  # worker raised: _worker_fn already unlinked

    def __del__(self):
        pool = getattr(self, "_pool", None)  # __init__ may have raised
        if pool is not None:
            pool.terminate()

    def __len__(self):
        return len(self._batch_sampler)
