"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py ~L400).

The reference uses multiprocessing workers passing NDArrays through POSIX
shared memory (cpu_shared storage).  On TPU the input pipeline's heavy
lifting (RecordIO decode/augment) belongs to the native C++ pipeline
(mxnet_tpu.io); this Python DataLoader covers the Dataset/transform path
with an optional thread pool — processes + shm are a poor fit for feeding a
single accelerator process and XLA host callbacks.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

import numpy as np

from ...base import MXNetError
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py ~L130)."""
    from ... import ndarray as nd
    from ...ndarray import NDArray

    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = np.asarray(data)
    return nd.array(arr, dtype=arr.dtype)


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler=None, last_batch=None,
                 batch_sampler=None, batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 pin_device_id: int = 0, prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = (RandomSampler(len(dataset)) if shuffle
                           else SequentialSampler(len(dataset)))
            elif shuffle:
                raise MXNetError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _load(self, indices) -> object:
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._load(batch)
            return
        # thread pool with bounded prefetch (double buffering)
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            batches = iter(self._batch_sampler)
            futures = []
            try:
                for _ in range(self._prefetch or self._num_workers):
                    futures.append(pool.submit(self._load, next(batches)))
            except StopIteration:
                pass
            while futures:
                fut = futures.pop(0)
                try:
                    futures.append(pool.submit(self._load, next(batches)))
                except StopIteration:
                    pass
                yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)
