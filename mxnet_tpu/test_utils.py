"""Test utilities (reference: python/mxnet/test_utils.py ~3k lines —
assert_almost_equal, check_numeric_gradient ~L900, check_consistency ~L1300,
rand_ndarray, default_context, with_seed; SURVEY §4.3).
"""
from __future__ import annotations

import functools
import logging
import os
import random as pyrandom
from typing import Callable, List, Optional

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_nd", "with_seed",
           "check_numeric_gradient", "check_consistency", "same", "retry",
           "DummyIter", "get_mnist", "list_gpus"]

_default_ctx = None


def default_context() -> Context:
    """Env-switchable default test context (MXNET_TEST_DEVICE=cpu|tpu|gpu)."""
    global _default_ctx
    if _default_ctx is not None:
        return _default_ctx
    dev = os.environ.get("MXNET_TEST_DEVICE", "cpu")
    from . import context as ctx_mod

    return getattr(ctx_mod, dev)() if hasattr(ctx_mod, dev) else cpu()


def set_default_context(ctx: Context):
    global _default_ctx
    _default_ctx = ctx


def _as_numpy(x):
    from .ndarray import NDArray

    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b) -> bool:
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol, atol = _get_tols(a, b, rtol, atol)
    return np.allclose(a.astype(np.float64), b.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan)


def _dtype_tol(dtype):
    name = np.dtype(dtype).name if np.dtype(dtype).kind != "V" else "bfloat16"
    return {
        "float16": (1e-2, 1e-2),
        "bfloat16": (2e-2, 2e-2),
        "float32": (1e-4, 1e-5),
        "float64": (1e-7, 1e-9),
    }.get(name, (0.0, 0.0))


def _get_tols(a, b, rtol, atol):
    rt_a, at_a = _dtype_tol(a.dtype)
    rt_b, at_b = _dtype_tol(b.dtype)
    return (rtol if rtol is not None else max(rt_a, rt_b),
            atol if atol is not None else max(at_a, at_b))


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Dtype-aware tolerance comparison (reference ~L500)."""
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    rtol, atol = _get_tols(a_np, b_np, rtol, atol)
    np.testing.assert_allclose(
        a_np.astype(np.float64), b_np.astype(np.float64), rtol=rtol,
        atol=atol, equal_nan=equal_nan,
        err_msg=f"{names[0]} and {names[1]} differ (rtol={rtol}, atol={atol})")


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None,
                 scale=1.0):
    from . import ndarray as nd

    if stype != "default":
        raise MXNetError("sparse stypes are emulated; use default")
    arr = np.random.uniform(-scale, scale, shape)
    return nd.array(arr, ctx=ctx or default_context(),
                    dtype=dtype or np.float32)


def with_seed(seed=None):
    """Per-test RNG reseeding decorator; logs the seed on failure so runs are
    reproducible (reference: with_seed ~L200)."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            this_seed = seed if seed is not None else np.random.randint(0, 2**31)
            np.random.seed(this_seed)
            pyrandom.seed(this_seed)
            from . import random as mx_random

            mx_random.seed(this_seed)
            try:
                return fn(*args, **kwargs)
            except Exception:
                logging.error("Test %s failed with seed %d", fn.__name__,
                              this_seed)
                raise

        return wrapper

    return decorator


def retry(n):
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return fn(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
                    np.random.seed()

        return wrapper

    return decorator


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=None):
    """Finite-difference check of the autograd path (reference ~L900).

    fn: callable taking NDArrays -> scalar NDArray loss.
    inputs: list of NDArrays (grads attached here).
    """
    from . import autograd
    from . import ndarray as nd

    for x in inputs:
        x.attach_grad()
    with autograd.record():
        loss = fn(*inputs)
    loss.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for i, x in enumerate(inputs):
        base = x.asnumpy().astype(np.float64)
        numeric = np.zeros_like(base)
        flat = base.ravel()
        num_flat = numeric.ravel()
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            x._set_data(__import__("jax").device_put(
                base.astype(np.float32).reshape(base.shape),
                x.context.jax_device))
            lp = float(fn(*inputs).asscalar())
            flat[j] = orig - eps
            x._set_data(__import__("jax").device_put(
                base.astype(np.float32).reshape(base.shape),
                x.context.jax_device))
            lm = float(fn(*inputs).asscalar())
            flat[j] = orig
            x._set_data(__import__("jax").device_put(
                base.astype(np.float32).reshape(base.shape),
                x.context.jax_device))
            num_flat[j] = (lp - lm) / (2 * eps)
        np.testing.assert_allclose(analytic[i], numeric, rtol=rtol,
                                   atol=atol or 1e-3,
                                   err_msg=f"gradient mismatch on input {i}")


def check_consistency(fn, ctx_list, inputs_np=None, rtol=None, atol=None):
    """Run `fn` under each context and compare outputs — the cpu-vs-tpu
    backend oracle (reference: check_consistency ~L1300, the main
    correctness harness for new device backends)."""
    from . import ndarray as nd

    results = []
    for ctx in ctx_list:
        with ctx:
            args = [nd.array(a, ctx=ctx) for a in (inputs_np or [])]
            out = fn(*args)
            results.append(_as_numpy(out))
    ref = results[0]
    for got, ctx in zip(results[1:], ctx_list[1:]):
        rt, at = _get_tols(ref, got, rtol, atol)
        np.testing.assert_allclose(
            ref.astype(np.float64), got.astype(np.float64), rtol=rt, atol=at,
            err_msg=f"inconsistent results between {ctx_list[0]} and {ctx}")
    return results


class DummyIter:
    """Infinite repeat of one batch (reference: DummyIter) — benchmarking
    without input-pipeline cost."""

    def __init__(self, real_iter):
        self.real_iter = real_iter
        self.provide_data = real_iter.provide_data
        self.provide_label = real_iter.provide_label
        self.batch_size = real_iter.batch_size
        self.the_batch = next(real_iter)

    def __iter__(self):
        return self

    def __next__(self):
        return self.the_batch

    next = __next__

    def reset(self):
        pass


def get_mnist():
    """Synthetic-fallback MNIST dict (reference downloads; zero-egress here)."""
    from .gluon.data.vision import MNIST

    train = MNIST(train=True)
    test = MNIST(train=False)
    return {
        "train_data": train._data.transpose(0, 3, 1, 2).astype(np.float32) / 255,
        "train_label": train._label,
        "test_data": test._data.transpose(0, 3, 1, 2).astype(np.float32) / 255,
        "test_label": test._label,
    }


def list_gpus():
    from .context import num_gpus

    return list(range(num_gpus()))
