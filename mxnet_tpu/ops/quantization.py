"""INT8 quantization ops.

Reference parity: src/operator/quantization/ — quantize_v2.cc,
dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc, quantization_utils.h (~15k LoC total; the
mkldnn int8 kernels' role is played by XLA int8 dot/conv, which lower to
the MXU with int32 accumulation).

Convention (matches the reference's int8 path): values are quantized
symmetrically about zero onto [-127, 127] ("shifted" uint8 mode is not
carried — the reference itself prefers int8 for mkldnn).  Every quantized
tensor travels with (min_range, max_range) f32 scalars, and
thresh = max(|min|, |max|), scale = 127 / thresh.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _thresh(min_r, max_r):
    return jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))


@register("_contrib_quantize_v2", differentiable=False)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """f32 -> int8 with calibrated or on-the-fly ranges (reference:
    quantize_v2.cc).  Returns (q_data, min_range, max_range)."""
    x = data.astype(jnp.float32)
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.asarray(float(min_calib_range), jnp.float32)
        mx = jnp.asarray(float(max_calib_range), jnp.float32)
    else:
        mn = jnp.min(x)
        mx = jnp.max(x)
    t = jnp.maximum(_thresh(mn, mx), 1e-12)
    scale = 127.0 / t
    q = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
    return q, -t, t


@register("_contrib_dequantize", differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    """int8/int32 -> f32 (reference: dequantize.cc)."""
    t = jnp.maximum(_thresh(min_range, max_range), 1e-12)
    if np.dtype(data.dtype) == np.int8:
        scale = t / 127.0
    else:  # int32 accumulator: range covers the accumulated magnitude
        scale = t / float(2**31 - 1)
    return data.astype(jnp.float32) * scale


@register("_contrib_requantize", differentiable=False)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 (reference: requantize.cc)."""
    t_in = jnp.maximum(_thresh(min_range, max_range), 1e-12)
    real = data.astype(jnp.float32) * (t_in / float(2**31 - 1))
    if min_calib_range is not None and max_calib_range is not None:
        t_out = jnp.maximum(
            _thresh(jnp.asarray(float(min_calib_range), jnp.float32),
                    jnp.asarray(float(max_calib_range), jnp.float32)), 1e-12)
    else:
        t_out = jnp.maximum(jnp.max(jnp.abs(real)), 1e-12)
    q = jnp.clip(jnp.round(real * (127.0 / t_out)), -127, 127).astype(jnp.int8)
    return q, -t_out, t_out


def _int32_range(t_data, t_weight):
    """(min,max) of the int32 accumulator in real units: products are
    bounded by t_data*t_weight scaled to 127*127 (quantization_utils.h
    kInt32Range bookkeeping)."""
    t = t_data * t_weight * (float(2**31 - 1) / (127.0 * 127.0))
    return -t, t


@register("_contrib_dequantize_int4", differentiable=False)
def dequantize_int4(packed, scales, group_size=32, cols=0):
    """Unpack 2-per-byte int4 weights and apply group-wise scales.

    ``packed`` is uint8 (rows, padded_cols // 2): each byte carries two
    signed nibbles along the input dim (low nibble = even column, the
    ``_quantize_weight_int4_np`` layout).  ``scales`` is f16/f32
    (rows, padded_cols // group_size) of per-group dequant scales
    (thresh / 7).  Returns the f32 weight (rows, cols) — ``cols`` slices
    off the zero padding the packer added to reach a group multiple.

    This runs IN-TRACE inside the serving engine's compiled decode/
    prefill bodies (precision/quantize.py int4 path): the executable's
    resident weight is the packed buffer, and XLA fuses the unpack +
    scale into the consumer matmul's operand read — the decode-bandwidth
    win weight-only int4 serving is for.
    """
    b = packed
    lo = jnp.bitwise_and(b, jnp.uint8(0x0F)).astype(jnp.int32)
    hi = jnp.right_shift(b, jnp.uint8(4)).astype(jnp.int32)
    # nibbles are two's-complement in [-8, 7] (quantized range [-7, 7])
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    rows = b.shape[0]
    q = jnp.stack([lo, hi], axis=-1).reshape(rows, -1)  # interleave
    g = int(group_size)
    w = (q.astype(jnp.float32).reshape(rows, -1, g)
         * scales.astype(jnp.float32)[..., None]).reshape(rows, -1)
    c = int(cols)
    return w[:, :c] if c and c != w.shape[1] else w


@register("_contrib_quantized_fully_connected", differentiable=False)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=None, no_bias=False,
                              flatten=True):
    """int8 x int8 -> int32 FC on the MXU (reference:
    quantized_fully_connected.cc).  Returns (int32 out, min_out, max_out);
    bias (f32) is folded in int32 units."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    acc = jax.lax.dot_general(
        x, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    t_d = _thresh(min_data, max_data)
    t_w = _thresh(min_weight, max_weight)
    mn, mx = _int32_range(t_d, t_w)
    if not no_bias and bias is not None:
        # bias arrives f32; convert to int32 accumulator units
        acc_scale = (127.0 * 127.0) / jnp.maximum(t_d * t_w, 1e-12)
        acc = acc + jnp.round(bias.astype(jnp.float32) * acc_scale
                              ).astype(jnp.int32)
    return acc, mn, mx


@register("_contrib_quantized_conv", differentiable=False)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None, kernel=(),
                   stride=(), dilate=(), pad=(), num_filter=1, num_group=1,
                   no_bias=False, layout=None):
    """int8 conv with int32 accumulation (reference: quantized_conv.cc)."""
    n = len(kernel)
    stride = tuple(stride) if stride else (1,) * n
    dilate = tuple(dilate) if dilate else (1,) * n
    pad = tuple(pad) if pad else (0,) * n
    spatial = "DHW"[-n:]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    acc = jax.lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    t_d = _thresh(min_data, max_data)
    t_w = _thresh(min_weight, max_weight)
    mn, mx = _int32_range(t_d, t_w)
    if not no_bias and bias is not None:
        acc_scale = (127.0 * 127.0) / jnp.maximum(t_d * t_w, 1e-12)
        b = jnp.round(bias.astype(jnp.float32) * acc_scale).astype(jnp.int32)
        acc = acc + b.reshape((1, -1) + (1,) * n)
    return acc, mn, mx
