"""Array creation ops (reference: src/operator/tensor/init_op.*)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import dtype_np
from .registry import register


@register("_zeros", differentiable=False)
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, dtype_np(dtype))


@register("_ones", differentiable=False)
def _ones(shape=(), dtype="float32"):
    return jnp.ones(shape, dtype_np(dtype))


@register("_full", differentiable=False)
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(shape, value, dtype_np(dtype))


@register("_arange", differentiable=False)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    arr = jnp.arange(start, stop, step, dtype_np(dtype))
    if repeat != 1:
        arr = jnp.repeat(arr, repeat)
    return arr


@register("_eye", differentiable=False)
def _eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(N, M if M else None, k=k, dtype=dtype_np(dtype))


@register("zeros_like", differentiable=False)
def zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like", differentiable=False)
def ones_like(x):
    return jnp.ones_like(x)


@register("_linspace", differentiable=False)
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=dtype_np(dtype))
