"""Typed op-parameter descriptors (reference: dmlc::Parameter /
DMLC_DECLARE_FIELD — 3rdparty/dmlc-core/include/dmlc/parameter.h — which
backs every operator's param struct, its docstring table, and the
string-keyed attr validation at the C ABI).

TPU-native shape: a descriptor per registered op, AUTO-DERIVED from the
pure jax function's signature (name + default → type), optionally enriched
with ranges/enums/docs via ``declare``.  ``describe`` renders the
reference-style parameter table; ``validate`` coerces and checks a kwargs
dict the way dmlc::Parameter::Init does (unknown key, type, range, enum).
"""
from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence

from ..base import MXNetError

__all__ = ["ParamField", "declare", "fields_of", "describe", "validate"]


class ParamField:
    """One typed op parameter (reference DMLC_DECLARE_FIELD chain)."""

    __slots__ = ("name", "type", "default", "doc", "lower", "upper", "enum")

    def __init__(self, name: str, type: str = "any", default: Any = None,
                 doc: str = "", lower=None, upper=None,
                 enum: Optional[Sequence] = None):
        self.name = name
        self.type = type
        self.default = default
        self.doc = doc
        self.lower = lower
        self.upper = upper
        self.enum = tuple(enum) if enum is not None else None

    def check(self, value):
        """Coerce + range/enum check; returns the coerced value."""
        v = value
        if v is None:
            return v  # None = unset/optional, always allowed
        try:
            if self.type == "int" and not isinstance(v, bool):
                v = int(v)
            elif self.type == "float":
                v = float(v)
            elif self.type == "bool":
                if isinstance(v, str):  # dmlc-style string parse
                    low = v.strip().lower()
                    if low in ("true", "1"):
                        v = True
                    elif low in ("false", "0"):
                        v = False
                    else:
                        raise ValueError(v)
                else:
                    v = bool(v)
            elif self.type == "str":
                v = str(v)
            elif self.type == "tuple" and not isinstance(v, (int, float)):
                if isinstance(v, str):  # "(2, 2)" — the C-ABI spelling
                    import ast

                    v = tuple(ast.literal_eval(v))
                else:
                    v = tuple(v)
        except (TypeError, ValueError, SyntaxError):
            raise MXNetError(
                f"parameter {self.name}={value!r} is not a valid "
                f"{self.type}")
        if self.lower is not None and v < self.lower:
            raise MXNetError(
                f"parameter {self.name}={v!r} below minimum {self.lower}")
        if self.upper is not None and v > self.upper:
            raise MXNetError(
                f"parameter {self.name}={v!r} above maximum {self.upper}")
        if self.enum is not None and v not in self.enum:
            raise MXNetError(
                f"parameter {self.name}={v!r} not in {self.enum}")
        return v

    def __repr__(self):
        extras = []
        if self.enum:
            extras.append(f"one of {self.enum}")
        if self.lower is not None or self.upper is not None:
            extras.append(f"range [{self.lower}, {self.upper}]")
        suffix = f" ({'; '.join(extras)})" if extras else ""
        return f"{self.type}, default={self.default!r}{suffix}"


# op name -> {param name -> ParamField}; populated lazily from signatures
# and eagerly by declare()
_DECLARED: Dict[str, Dict[str, ParamField]] = {}


def _infer_type(default) -> str:
    if isinstance(default, bool):
        return "bool"
    if isinstance(default, int):
        return "int"
    if isinstance(default, float):
        return "float"
    if isinstance(default, str):
        return "str"
    if isinstance(default, (tuple, list)):
        return "tuple"
    return "any"


def declare(op_name: str, *fields: ParamField):
    """Enrich (or add) typed fields for an op — the DMLC_DECLARE_FIELD
    analog for ranges, enums and docs the signature can't express."""
    slot = _DECLARED.setdefault(op_name, {})
    for f in fields:
        slot[f.name] = f


def fields_of(op_name: str) -> List[ParamField]:
    """All parameter fields of an op: signature-derived defaults merged
    with any declare()d enrichments."""
    from .registry import get_op

    op = get_op(op_name)
    sig = inspect.signature(op.fn)
    declared = _DECLARED.get(op_name, {})
    out = []
    for p in sig.parameters.values():
        if p.default is p.empty:
            continue  # array input, not an attr
        if p.name in declared:
            out.append(declared[p.name])
        else:
            out.append(ParamField(p.name, _infer_type(p.default),
                                  default=p.default))
    # declared fields that aren't in the signature (e.g. **attrs ops)
    names = {f.name for f in out}
    out.extend(f for n, f in declared.items() if n not in names)
    return out


def describe(op_name: str) -> str:
    """Reference-style parameter table for an op's docstring."""
    fields = fields_of(op_name)
    if not fields:
        return f"{op_name}: no parameters"
    width = max(len(f.name) for f in fields) + 2
    lines = [f"Parameters of {op_name}:"]
    for f in fields:
        lines.append(f"  {f.name:<{width}}{f!r}"
                     + (f" — {f.doc}" if f.doc else ""))
    return "\n".join(lines)


def validate(op_name: str, attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Coerce + check an attrs dict against the op's fields (reference
    dmlc::Parameter::Init): unknown keys and out-of-range values raise."""
    fields = {f.name: f for f in fields_of(op_name)}
    out = {}
    for k, v in attrs.items():
        if k not in fields:
            raise MXNetError(
                f"{op_name}: unknown parameter {k!r} (valid: "
                f"{sorted(fields)})")
        out[k] = fields[k].check(v)
    return out


def validate_known(op_name: str, attrs: Dict[str, Any]) -> None:
    """Range/enum-check the attrs that have declared fields; tolerate
    unknown keys (ops with **attrs).  This is the hook on the registry's
    jit-cache-miss path: it must never coerce, only reject bad values."""
    declared = _DECLARED.get(op_name)
    if not declared:
        return
    for k, v in attrs.items():
        f = declared.get(k)
        if f is not None:
            f.check(v)


# ---------------------------------------------------------------------------
# enriched declarations for the heavily-parameterized layer ops (the ones
# whose reference param structs carry ranges/enums)
# ---------------------------------------------------------------------------
declare("Pooling",
        ParamField("pool_type", "str", "max",
                   enum=("max", "avg", "sum", "lp"),
                   doc="pooling monoid"),
        ParamField("pooling_convention", "str", "valid",
                   enum=("valid", "full"), doc="output-shape rounding"),
        ParamField("p_value", "int", 2, lower=1,
                   doc="Lp-pooling exponent"))
declare("Activation",
        ParamField("act_type", "str", "relu",
                   enum=("relu", "sigmoid", "tanh", "softrelu",
                         "softsign")))
declare("Dropout",
        ParamField("p", "float", 0.5, lower=0.0, upper=1.0,
                   doc="fraction of units dropped"),
        ParamField("mode", "str", "training",
                   enum=("training", "always")))
declare("BatchNorm",
        ParamField("eps", "float", 1e-3, lower=0.0),
        ParamField("momentum", "float", 0.9, lower=0.0, upper=1.0))
declare("Convolution",
        ParamField("num_filter", "int", 1, lower=1),
        ParamField("num_group", "int", 1, lower=1))
declare("LeakyReLU",
        ParamField("act_type", "str", "leaky",
                   enum=("leaky", "prelu", "rrelu", "elu", "selu",
                         "gelu")))
declare("softmax", ParamField("axis", "int", -1))
declare("RNN",
        ParamField("mode", "str", "lstm",
                   enum=("lstm", "gru", "rnn_relu", "rnn_tanh")),
        ParamField("state_size", "int", 0, lower=0),
        ParamField("num_layers", "int", 1, lower=1),
        ParamField("p", "float", 0.0, lower=0.0, upper=1.0))
declare("Correlation",
        ParamField("kernel_size", "int", 1, lower=1),
        ParamField("max_displacement", "int", 1, lower=0),
        ParamField("stride1", "int", 1, lower=1),
        ParamField("stride2", "int", 1, lower=1),
        ParamField("pad_size", "int", 0, lower=0))
