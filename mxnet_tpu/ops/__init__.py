"""Operator registry and implementations (jax/lax-backed).

Reference parity: the nnvm op registry + src/operator/* kernel tree
(NNVM_REGISTER_OP; FCompute dispatch — include/mxnet/op_attr_types.h ~L60).
On TPU each op is a pure jax function; XLA performs the kernel fusion that
mshadow expression templates / FusedOp RTC do in the reference.
"""
from .registry import Operator, register, get_op, invoke, list_ops
from . import params  # noqa: F401  (typed param descriptors)

from . import elemwise  # noqa: F401
from . import creation  # noqa: F401
from . import reduce_ops  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import cv_ops  # noqa: F401
from . import quantization  # noqa: F401
from . import warp_ops  # noqa: F401
