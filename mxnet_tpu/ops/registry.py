"""Declarative operator registry.

Reference parity: NNVM op registry (3rdparty/tvm/nnvm/include/nnvm/op.h) +
imperative dispatch (src/imperative/imperative.cc Imperative::Invoke ~L90,
imperative_utils.h PushFCompute ~L400).

TPU-native design:
  * an Operator's FCompute is a pure jax function ``fn(*arrays, **attrs)``;
  * eager calls go through a per-(op, attrs) ``jax.jit`` cache — jax's own
    C++ dispatch then caches per input signature, which plays the role of
    the reference's engine push fast-path;
  * shape/dtype inference falls out of jax abstract evaluation — there are
    no separate FInferShape/FInferType functions to keep in sync;
  * gradients come from ``jax.vjp`` captured at execution time (autograd.py),
    replacing per-op FGradient registrations;
  * inside a HybridBlock trace the inputs are jax tracers: the op function
    is inlined into the outer jaxpr (CachedOp), with no tape recording —
    exactly the reference split between Imperative::Invoke and CachedOp.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..base import MXNetError, canonical_kwargs
from .. import engine
from ..passes import hooks as _pass_hooks

__all__ = ["Operator", "register", "get_op", "invoke", "list_ops"]

_OPS: Dict[str, "Operator"] = {}


class Operator:
    """A registered op: name, pure jax FCompute, and differentiability."""

    def __init__(self, name: str, fn: Callable, differentiable: bool = True,
                 doc: Optional[str] = None):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.__doc__ = doc or fn.__doc__
        self._jit_cache: Dict[Any, Callable] = {}
        self._bwd_cache: Dict[Any, Callable] = {}

    def jitted(self, attrs: dict) -> Callable:
        key = canonical_kwargs(attrs)
        jfn = self._jit_cache.get(key)
        if jfn is None:
            # first sight of this attr combo: typed validation (reference
            # dmlc::Parameter::Init at op instantiation); cache hits skip it
            from . import params as _params

            _params.validate_known(self.name, attrs)
            fn = self.fn

            @functools.wraps(fn)
            def call(*arrays):
                return fn(*arrays, **attrs)

            import jax

            jfn = jax.jit(call)
            jfn._canonical_key = key
            self._jit_cache[key] = jfn
        return jfn

    def bwd_jitted(self, jfn: Callable, mask: tuple) -> Callable:
        """Compiled backward for this (attrs, detach-mask) signature.

        The eager tape defers vjp construction to backward time (recording
        an op costs one cached-jit forward, ~15µs, instead of a ~650µs
        jax.vjp re-trace per call); the vjp itself runs through this cached
        jit — forward is recomputed inside it (remat-style), which XLA
        dead-code-eliminates down to the residuals the backward needs.

        `jfn` must come from self.jitted() (its canonical key is reused so
        the hot path canonicalizes attrs exactly once).
        """
        key = (jfn._canonical_key, mask)
        bwd = self._bwd_cache.get(key)
        if bwd is None:
            import jax

            fwd = _wrap_masked(jfn, mask)

            def bwd_fn(xs, ct):
                return jax.vjp(fwd, *xs)[1](ct)

            bwd = jax.jit(bwd_fn)
            self._bwd_cache[key] = bwd
        return bwd

    def __repr__(self):
        return f"<Operator {self.name}>"


def register(name: Optional[str] = None, differentiable: bool = True):
    """Decorator: register a pure jax function as an operator."""

    def deco(fn: Callable) -> Callable:
        opname = name or fn.__name__
        if opname in _OPS:
            raise MXNetError(f"op {opname!r} registered twice")
        _OPS[opname] = Operator(opname, fn, differentiable=differentiable)
        return fn

    return deco


def get_op(name: str) -> Operator:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"unknown operator {name!r}") from None


def list_ops() -> List[str]:
    return sorted(_OPS)


def _is_tracer(x) -> bool:
    import jax

    return isinstance(x, jax.core.Tracer)


def _is_float(arr) -> bool:
    from ..base import is_float_dtype

    return is_float_dtype(arr.dtype)


def invoke(op: Operator, inputs: Sequence, out=None, ctx=None, **attrs):
    """Execute `op` on NDArray inputs; returns NDArray or list of NDArrays.

    This is the single dispatch point shared by eager mode, autograd
    recording, and HybridBlock tracing (reference: MXImperativeInvokeEx).
    `ctx` only matters for zero-input (creation) ops; otherwise outputs
    follow their inputs' device, as in the reference.
    """
    from .. import profiler

    if profiler.is_recording() and not any(_is_tracer(x._data)
                                           for x in inputs):
        # per-op aggregate stats (reference: ThreadedEngine profiler
        # brackets -> aggregate_stats.cc).  Blocking for the timing
        # serializes dispatch — profiling overhead, as in the reference.
        return profiler.timed_call(op.name, _invoke_impl, op, inputs,
                                   out=out, ctx=ctx, **attrs)
    return _invoke_impl(op, inputs, out=out, ctx=ctx, **attrs)


def _invoke_impl(op: Operator, inputs: Sequence, out=None, ctx=None, **attrs):
    from ..ndarray import NDArray
    from .. import autograd

    # THE pass-pipeline consultation (docs/PRECISION.md §Pass pipeline):
    # the ONE module global dispatch reads.  Empty tuple when no pass is
    # active — that falsy check is the entire passes-off cost, exactly
    # the contract the PR 15 AMP global established.  Active hooks (the
    # AMP cast pass, ...) rewrite this call's inputs; trace-time kernel
    # substitution consults the same tuple on the traced branch below.
    # mxlint pins this: any OTHER module-global consultation added here
    # is a pass-outside-pipeline finding.
    op_hooks = _pass_hooks._OP_HOOKS
    if op_hooks and inputs:
        for h in op_hooks:
            inputs = h.rewrite_inputs(op.name, inputs)
    arrays = [x._data for x in inputs]
    if inputs:
        ctx = inputs[0].context
    elif ctx is None:
        from ..context import current_context

        ctx = current_context()

    traced = any(_is_tracer(a) for a in arrays)
    if traced:
        # hybridized trace: same typed validation as the eager jit-miss
        # path (once per trace, not per step)
        from . import params as _params

        _params.validate_known(op.name, attrs)
        arrays = _stop_detached(arrays, inputs)
        fn = op.fn
        if op_hooks:
            # fused-kernel substitution (passes/builtin.FusedKernelPass):
            # inside a trace an active pass may swap this op-class's
            # FCompute for a registered Pallas kernel; eager dispatch
            # never consults the kernel registry
            for h in op_hooks:
                alt = h.substitute(op.name, attrs)
                if alt is not None:
                    fn = alt
        outs = fn(*arrays, **attrs)
    elif not arrays:
        # creation op: place the result on ctx's device
        import jax

        with jax.default_device(ctx.jax_device):
            outs = op.jitted(attrs)()
    else:
        jfn = op.jitted(attrs)
        if (op.name == "Embedding" and attrs.get("sparse_grad")
                and autograd.is_recording()):
            # row_sparse backward: record a custom pullback that yields a
            # (indices, values) cotangent for the weight instead of a
            # dense vocab-sized scatter (reference: EmbeddingOpBackward
            # row_sparse path, src/operator/tensor/indexing_op.h)
            outs = jfn(*arrays)
            data_arr, weight_arr = arrays
            vocab, dim = weight_arr.shape

            def sparse_vjp(ct):
                import jax.numpy as jnp

                ids = jnp.clip(data_arr.astype(jnp.int32), 0,
                               vocab - 1).reshape(-1)
                vals = ct.reshape(-1, dim)
                return [None, autograd._RowSparseCT(ids, vals,
                                                    weight_arr.shape)]

            autograd.record_node(sparse_vjp, arrays, [outs],
                                 input_nds=inputs)
        elif (
            autograd.is_recording()
            and op.differentiable
            and arrays
            and any(_is_float(a) for a in arrays)
        ):
            # fast recording: forward through the cached jit (same cost as
            # un-recorded eager); the vjp is DEFERRED to backward time and
            # runs through a per-(op, attrs, mask) compiled backward —
            # recording no longer pays a jax.vjp re-trace per call
            mask = _detach_mask(inputs)
            wrapped = _wrap_masked(jfn, mask)
            outs = wrapped(*arrays)
            bwd = op.bwd_jitted(jfn, mask)
            in_arrays = tuple(arrays)

            def vjp_fn(ct, _bwd=bwd, _xs=in_arrays):
                return _bwd(_xs, ct)

            seq = isinstance(outs, (tuple, list))
            out_list = list(outs) if seq else [outs]
            # identity-like ops (e.g. SVMOutput's forward) can return an
            # INPUT array object unchanged; the tape keys nodes by
            # id(array), so an aliased output would both seed the head
            # cotangent and receive the op's vjp — break the alias
            in_ids = {id(a) for a in arrays}
            if any(id(o) in in_ids for o in out_list):
                import jax.numpy as jnp

                out_list = [jnp.copy(o) if id(o) in in_ids else o
                            for o in out_list]
                outs = type(outs)(out_list) if seq else out_list[0]
            autograd.record_node(vjp_fn, arrays, out_list, input_nds=inputs,
                                 fwd_fn=wrapped)
        else:
            outs = jfn(*arrays)
        if engine.is_naive():
            import jax

            jax.block_until_ready(outs)

    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]
    results = [NDArray(o, ctx=ctx) for o in out_list]
    if out is not None:
        if multi:
            raise MXNetError(f"out= not supported for multi-output op {op.name}")
        out._set_data(results[0]._data)
        return out
    return results if multi else results[0]


def _vjp(jfn, arrays):
    import jax

    return jax.vjp(jfn, *arrays)


def _stop_detached(arrays, inputs):
    import jax

    return [
        jax.lax.stop_gradient(a) if getattr(nd, "_detached", False) else a
        for a, nd in zip(arrays, inputs)
    ]


def _detach_mask(inputs):
    return tuple(bool(getattr(nd, "_detached", False)) for nd in inputs)


def _wrap_masked(fn, mask):
    """Stop gradient flow through the mask-selected arguments (the single
    implementation both the forward wrapper and the compiled backward use,
    so detach semantics can't drift between them)."""
    if not any(mask):
        return fn
    import jax

    def wrapped(*arrays):
        return fn(*[
            jax.lax.stop_gradient(a) if m else a for a, m in zip(arrays, mask)
        ])

    return wrapped


def _wrap_detached(fn, inputs):
    """Stop gradient flow through inputs marked detach()ed, without copying
    their buffers or changing their tape identity."""
    return _wrap_masked(fn, _detach_mask(inputs))


def invoke_by_name(name: str, inputs, out=None, **attrs):
    return invoke(get_op(name), inputs, out=out, **attrs)


def invoke_fn(fn, inputs, out=None):
    """Execute an ad-hoc pure jax function on NDArray inputs with full
    autograd-recording / tracing support but no jit cache (used by NDArray
    indexing and other closures whose attrs aren't hashable)."""
    from ..ndarray import NDArray
    from .. import autograd

    arrays = [x._data for x in inputs]
    ctx = inputs[0].context if inputs else None

    traced = any(_is_tracer(a) for a in arrays)
    if not traced and autograd.is_recording() and any(_is_float(a) for a in arrays):
        wrapped = _wrap_detached(fn, inputs)
        outs, vjp_fn = _vjp(wrapped, arrays)
        out_list = outs if isinstance(outs, (tuple, list)) else [outs]
        autograd.record_node(vjp_fn, arrays, list(out_list), input_nds=inputs,
                             fwd_fn=wrapped)
    else:
        if traced:
            arrays = _stop_detached(arrays, inputs)
        outs = fn(*arrays)
        if not traced and engine.is_naive():
            import jax

            jax.block_until_ready(outs)

    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]
    results = [NDArray(o, ctx=ctx) for o in out_list]
    if out is not None:
        if multi:
            raise MXNetError("out= not supported for multi-output functions")
        out._set_data(results[0]._data)
        return out
    return results if multi else results[0]
