"""Contrib operators: fused attention (reference src/operator/contrib/
transformer.cc interleaved_matmul_selfatt_qk/valatt ~L1-300, superseded
here by a full flash-attention fusion).

CV contrib ops (NMS / multibox / ROI) live in cv_ops.py.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _dense_attention(q, k, v, causal, sm_scale):
    s = jnp.einsum("nqd,nkd->nqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((qpos >= kpos)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("nqk,nkd->nqd", p, v.astype(jnp.float32)).astype(q.dtype)


@register("_contrib_flash_attention")
def flash_attention_op(q, k, v, causal=False, sm_scale=None):
    """Fused softmax(q k^T) v.  q/k/v: (N, L, D) or (B, H, L, D).

    Pallas blockwise kernel on TPU; dense jnp composition elsewhere
    (XLA still fuses the chain, it just materialises scores).  Inside a
    DataParallelStep(ring_attention=True) trace with an active sp axis,
    3-d inputs route through the sequence-parallel ring kernel
    (parallel/ring.py): K/V rotate over ICI via ppermute and the full
    (L, L) score matrix never exists on any device.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    from ..parallel import ring_scope

    scope = ring_scope()
    if scope is not None and q.ndim == 3:
        mesh, batch_axes, mode = scope
        shape = dict(mesh.shape)
        sp = shape.get("sp", 1)
        n_batch = 1
        for a in batch_axes:
            n_batch *= shape.get(a, 1)
        # route to the SP kernel only when shard_map's divisibility holds
        # for EVERY operand dim it shards (self-attention, seq and batch
        # dims divisible; Ulysses also shards heads) — anything else
        # silently keeps the dense/Pallas path that runs the same shapes
        # without the scope
        ok = (sp > 1
              and q.shape[1] == k.shape[1] == v.shape[1]
              and q.shape[1] % sp == 0
              and q.shape[0] % max(n_batch, 1) == 0)
        if ok and mode == "ulysses":
            ok = (q.shape[0] // max(n_batch, 1)) % sp == 0
        if ok:
            if mode == "ulysses":
                from ..parallel.ulysses import ulysses_self_attention as sp_fn
            else:
                from ..parallel.ring import ring_self_attention as sp_fn
            return sp_fn(mesh, q, k, v, causal=causal, sm_scale=sm_scale,
                         batch_axes=batch_axes or None)
    from . import pallas as _pk

    if _pk.enabled() and _pk.use_compiled():
        return _pk.flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if q.ndim == 4:
        b, h = q.shape[:2]
        out = _dense_attention(q.reshape(b * h, *q.shape[2:]),
                               k.reshape(b * h, *k.shape[2:]),
                               v.reshape(b * h, *v.shape[2:]),
                               causal, sm_scale)
        return out.reshape(b, h, *out.shape[1:])
    return _dense_attention(q, k, v, causal, sm_scale)


@register("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """(L, B, 3*H*D) interleaved qkv -> scaled q k^T scores (B*H, L, L).

    Reference semantics: scores scaled by 1/sqrt(D) (transformer.cc ~L40).
    """
    L, B, P = queries_keys_values.shape
    D = P // (3 * heads)
    x = queries_keys_values.reshape(L, B, heads, 3, D)
    q = x[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    k = x[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    return jnp.einsum("nqd,nkd->nqk", q, k) / math.sqrt(D)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """attention (B*H, L, L) @ v from interleaved qkv -> (L, B, H*D)."""
    L, B, P = queries_keys_values.shape
    D = P // (3 * heads)
    x = queries_keys_values.reshape(L, B, heads, 3, D)
    v = x[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    out = jnp.einsum("nqk,nkd->nqd", attention, v)
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(
        L, B, heads * D)


# ---------------------------------------------------------------------------
# adaptive pooling / deformable convolution / CTC (r2 compat tail)
# ---------------------------------------------------------------------------
@register("_contrib_AdaptiveAvgPooling2D")
def adaptive_avg_pooling(data, output_size=1):
    """Adaptive average pooling to a fixed output grid (reference:
    src/operator/contrib/adaptive_avg_pooling.cc).

    Output cell (i, j) averages input window [floor(i*H/H0), ceil((i+1)*H/H0))
    — computed via a 2-D integral image so uneven windows stay one fused
    gather, not a python loop per cell.
    """
    import numpy as np

    if isinstance(output_size, int):
        oh = ow = int(output_size)
    else:
        oh, ow = (int(output_size[0]), int(output_size[-1]))
    n, c, h, w = data.shape
    x32 = data.astype(jnp.float32)
    # integral image with a leading zero row/col
    integ = jnp.pad(jnp.cumsum(jnp.cumsum(x32, axis=2), axis=3),
                    ((0, 0), (0, 0), (1, 0), (1, 0)))
    hs = np.floor(np.arange(oh) * h / oh).astype(np.int32)
    he = np.ceil((np.arange(oh) + 1) * h / oh).astype(np.int32)
    ws = np.floor(np.arange(ow) * w / ow).astype(np.int32)
    we = np.ceil((np.arange(ow) + 1) * w / ow).astype(np.int32)
    area = ((he - hs)[:, None] * (we - ws)[None, :]).astype(np.float32)
    s = (integ[:, :, he][:, :, :, we] - integ[:, :, hs][:, :, :, we]
         - integ[:, :, he][:, :, :, ws] + integ[:, :, hs][:, :, :, ws])
    return (s / area).astype(data.dtype)


@register("histogram")
def histogram(data, *bin_arr, bin_cnt=None, range=None, bins=10):
    """np.histogram semantics (reference: src/operator/tensor/histogram.cc).

    Either bin_cnt+range (uniform bins) or an explicit bin-edge array.
    Returns (counts, bin_edges)."""
    x = data.reshape(-1).astype(jnp.float32)
    if bin_arr:
        edges = bin_arr[0].astype(jnp.float32)
        nbins = edges.shape[0] - 1
        idx = jnp.searchsorted(edges, x, side="right") - 1
        # right-most edge is inclusive (numpy semantics)
        idx = jnp.where(x == edges[-1], nbins - 1, idx)
        valid = (idx >= 0) & (idx < nbins)
        counts = jnp.zeros((nbins,), jnp.int32).at[
            jnp.where(valid, idx, 0)].add(valid.astype(jnp.int32))
        return counts, edges
    cnt = int(bin_cnt if bin_cnt is not None else bins)
    if range is None:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = jnp.asarray(range[0], jnp.float32), jnp.asarray(
            range[1], jnp.float32)
    span = jnp.where(hi > lo, hi - lo, 1.0)
    idx = jnp.floor((x - lo) / span * cnt).astype(jnp.int32)
    idx = jnp.clip(idx, 0, cnt - 1)
    valid = (x >= lo) & (x <= hi)
    counts = jnp.zeros((cnt,), jnp.int32).at[
        jnp.where(valid, idx, 0)].add(valid.astype(jnp.int32))
    edges = lo + (hi - lo) * jnp.arange(cnt + 1, dtype=jnp.float32) / cnt
    return counts, edges


def _bilinear_gather(img, y, x):
    """img (C, H, W); y/x arbitrary equal shapes of float coords.
    Zero padding outside (reference deformable conv im2col behavior)."""
    c, h, w = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1, wx1 = y - y0, x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def at(yy, xx):
        inside = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
        xc = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
        v = img[:, yc, xc]
        return jnp.where(inside, v, 0.0)

    return (at(y0, x0) * (wy0 * wx0) + at(y0, x0 + 1) * (wy0 * wx1)
            + at(y0 + 1, x0) * (wy1 * wx0) + at(y0 + 1, x0 + 1) * (wy1 * wx1))


@register("_contrib_DeformableConvolution")
def deformable_convolution(data, offset, weight, *bias, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=1, num_group=1, num_deformable_group=1,
                           no_bias=False, workspace=1024, layout=None):
    """Deformable convolution v1 (reference: src/operator/contrib/
    deformable_convolution.cc — Dai et al. 2017).

    offset: (N, 2*dg*kh*kw, H0, W0), ordered (y, x) per kernel tap.
    Implementation: bilinear-sample a deformed im2col volume, then one
    einsum onto the MXU — the gather is the only non-matmul work.
    """
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    n, cin, h, w = data.shape
    h0 = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    w0 = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = num_deformable_group
    x32 = data.astype(jnp.float32)
    off = offset.astype(jnp.float32).reshape(n, dg, kh * kw, 2, h0, w0)

    base_y = (jnp.arange(h0) * sh - ph)[:, None]  # (h0, 1)
    base_x = (jnp.arange(w0) * sw - pw)[None, :]  # (1, w0)
    ky = (jnp.arange(kh) * dh)[:, None].repeat(kw, 1).reshape(-1)  # (kh*kw,)
    kx = (jnp.arange(kw) * dw)[None, :].repeat(kh, 0).reshape(-1)

    # sample positions: (dg, kh*kw, h0, w0)
    y_pos = base_y[None, None] + ky[None, :, None, None] + off[:, :, :, 0]
    x_pos = base_x[None, None] + kx[None, :, None, None] + off[:, :, :, 1]

    cpg = cin // dg  # channels per deformable group

    def sample_one(img, yp, xp):
        # img (cin, h, w); yp/xp (dg, K, h0, w0) -> (cin, K, h0, w0)
        outs = []
        for g in range(dg):
            outs.append(_bilinear_gather(img[g * cpg:(g + 1) * cpg],
                                         yp[g], xp[g]))
        return jnp.concatenate(outs, axis=0)

    cols = jax.vmap(sample_one)(x32, y_pos, x_pos)  # (n, cin, K, h0, w0)
    wmat = weight.astype(jnp.float32).reshape(num_filter, cin // num_group,
                                              kh * kw)
    if num_group == 1:
        out = jnp.einsum("nckhw,fck->nfhw", cols, wmat)
    else:
        cg = cin // num_group
        fg = num_filter // num_group
        cols_g = cols.reshape(n, num_group, cg, kh * kw, h0, w0)
        wmat_g = wmat.reshape(num_group, fg, cg, kh * kw)
        out = jnp.einsum("ngckhw,gfck->ngfhw", cols_g, wmat_g).reshape(
            n, num_filter, h0, w0)
    out = out.astype(data.dtype)
    if not no_bias and bias:
        out = out + bias[0].reshape(1, -1, 1, 1)
    return out
