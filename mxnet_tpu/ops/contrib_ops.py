"""Contrib operators: fused attention (reference src/operator/contrib/
transformer.cc interleaved_matmul_selfatt_qk/valatt ~L1-300, superseded
here by a full flash-attention fusion).

CV contrib ops (NMS / multibox / ROI) live in cv_ops.py.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _dense_attention(q, k, v, causal, sm_scale):
    s = jnp.einsum("nqd,nkd->nqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((qpos >= kpos)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("nqk,nkd->nqd", p, v.astype(jnp.float32)).astype(q.dtype)


@register("_contrib_flash_attention")
def flash_attention_op(q, k, v, causal=False, sm_scale=None):
    """Fused softmax(q k^T) v.  q/k/v: (N, L, D) or (B, H, L, D).

    Pallas blockwise kernel on TPU; dense jnp composition elsewhere
    (XLA still fuses the chain, it just materialises scores).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    from . import pallas as _pk

    if _pk.enabled() and _pk.use_compiled():
        return _pk.flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if q.ndim == 4:
        b, h = q.shape[:2]
        out = _dense_attention(q.reshape(b * h, *q.shape[2:]),
                               k.reshape(b * h, *k.shape[2:]),
                               v.reshape(b * h, *v.shape[2:]),
                               causal, sm_scale)
        return out.reshape(b, h, *out.shape[1:])
    return _dense_attention(q, k, v, causal, sm_scale)


@register("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """(L, B, 3*H*D) interleaved qkv -> scaled q k^T scores (B*H, L, L).

    Reference semantics: scores scaled by 1/sqrt(D) (transformer.cc ~L40).
    """
    L, B, P = queries_keys_values.shape
    D = P // (3 * heads)
    x = queries_keys_values.reshape(L, B, heads, 3, D)
    q = x[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    k = x[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    return jnp.einsum("nqd,nkd->nqk", q, k) / math.sqrt(D)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """attention (B*H, L, L) @ v from interleaved qkv -> (L, B, H*D)."""
    L, B, P = queries_keys_values.shape
    D = P // (3 * heads)
    x = queries_keys_values.reshape(L, B, heads, 3, D)
    v = x[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(B * heads, L, D)
    out = jnp.einsum("nqk,nkd->nqd", attention, v)
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(
        L, B, heads * D)
