"""Detection / CV operators (SSD, Faster-RCNN families).

Reference parity: src/operator/contrib/ — multibox_prior/target/detection.*
(SSD anchors/matching/decode), bounding_box.* (box_nms ~L100, box_iou),
roi_align.*, proposal.* (RPN), bipartite matching.

TPU-native design: every op is static-shape and batched.  The reference's
dynamic-length outputs (NMS survivors, proposal lists) become fixed-size
tensors with -1/padding rows, exactly like the reference's own box_nms
convention — which is also the XLA-friendly convention (no dynamic shapes,
everything maps onto vectorized compare/select + a short sequential
suppression loop via lax.fori_loop; no atomics needed unlike the CUDA
kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------


def _to_corner(boxes, fmt):
    if fmt == "corner":
        return boxes
    # center: (cx, cy, w, h) -> (x1, y1, x2, y2)
    cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


def _pair_iou(lhs, rhs):
    """IoU between every box in lhs (..., N, 4) and rhs (..., M, 4),
    corner format -> (..., N, M)."""
    lx1, ly1, lx2, ly2 = jnp.split(lhs[..., :, None, :], 4, axis=-1)
    rx1, ry1, rx2, ry2 = jnp.split(rhs[..., None, :, :], 4, axis=-1)
    ix1 = jnp.maximum(lx1, rx1)
    iy1 = jnp.maximum(ly1, ry1)
    ix2 = jnp.minimum(lx2, rx2)
    iy2 = jnp.minimum(ly2, ry2)
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = (iw * ih)[..., 0]
    area_l = ((lx2 - lx1) * (ly2 - ly1))[..., 0]
    area_r = ((rx2 - rx1) * (ry2 - ry1))[..., 0]
    union = area_l + area_r - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou")
def box_iou(lhs, rhs, format="corner"):
    """Reference: src/operator/contrib/bounding_box.cc (box_iou)."""
    return _pair_iou(_to_corner(lhs, format), _to_corner(rhs, format))


def _greedy_nms_mask(boxes, scores, valid, overlap_thresh, classes=None,
                     force_suppress=True):
    """Greedy NMS on score-desc-sorted inputs -> keep mask (N,).

    Sequential greedy selection via fori_loop over the (topk-bounded) box
    count; the IoU matrix is computed once, vectorized on the MXU-friendly
    path — the CUDA kernel's bitmask blocks aren't needed.
    """
    n = boxes.shape[0]
    iou = _pair_iou(boxes, boxes)
    if classes is not None and not force_suppress:
        same = classes[:, None] == classes[None, :]
        iou = jnp.where(same, iou, 0.0)
    overlap = iou > overlap_thresh

    def body(i, state):
        keep, suppressed = state
        keep_i = valid[i] & ~suppressed[i]
        keep = keep.at[i].set(keep_i)
        suppressed = suppressed | (keep_i & overlap[i])
        return keep, suppressed

    keep0 = jnp.zeros((n,), bool)
    sup0 = jnp.zeros((n,), bool)
    keep, _ = jax.lax.fori_loop(0, n, body, (keep0, sup0))
    return keep


@register("_contrib_box_nms")
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Reference: src/operator/contrib/bounding_box.cc (BoxNMS ~L100).
    Suppressed/invalid rows become -1, shape preserved."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    batch_shape = data.shape[:-2]
    n, k = data.shape[-2:]
    flat = data.reshape((-1, n, k))

    def one(d):
        scores = d[:, score_index]
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid = valid & (d[:, id_index] != background_id)
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        d_sorted = d[order]
        valid_sorted = valid[order]
        if topk > 0:
            in_topk = jnp.arange(n) < topk
            valid_sorted = valid_sorted & in_topk
        boxes = _to_corner(d_sorted[:, coord_start:coord_start + 4], in_format)
        cls = d_sorted[:, id_index] if id_index >= 0 else None
        keep = _greedy_nms_mask(boxes, d_sorted[:, score_index], valid_sorted,
                                overlap_thresh, classes=cls,
                                force_suppress=force_suppress)
        out = jnp.where(keep[:, None], d_sorted, -jnp.ones_like(d_sorted))
        # stable-compact kept rows to the front (reference behavior)
        rank = jnp.where(keep, jnp.arange(n), n + jnp.arange(n))
        return out[jnp.argsort(rank)]

    out = jax.vmap(one)(flat).reshape(batch_shape + (n, k))
    return out[0] if squeeze else out


@register("_contrib_bipartite_matching")
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1):
    """Greedy bipartite matching (reference:
    src/operator/contrib/bounding_box.cc BipartiteMatching).
    data (..., N, M) pairwise scores -> (row_match (..., N), col_match (..., M))."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]

    def one(scores):
        n, m = scores.shape
        sign = 1.0 if is_ascend else -1.0
        steps = n if topk <= 0 else min(topk, n)

        def body(_, state):
            row, col, s = state
            # best remaining pair
            best = jnp.unravel_index(jnp.argmax(jnp.where(
                jnp.isfinite(s), -sign * s, -jnp.inf)), s.shape)
            i, j = best
            ok = jnp.isfinite(s[i, j]) & (
                (s[i, j] >= threshold) if not is_ascend else
                (s[i, j] <= threshold))
            row = jnp.where(ok, row.at[i].set(j), row)
            col = jnp.where(ok, col.at[j].set(i), col)
            s = jnp.where(ok, s.at[i, :].set(jnp.inf * sign), s)
            s = jnp.where(ok, s.at[:, j].set(jnp.inf * sign), s)
            return row, col, s

        row0 = -jnp.ones((n,), jnp.float32)
        col0 = -jnp.ones((m,), jnp.float32)
        row, col, _ = jax.lax.fori_loop(
            0, steps, body, (row0, col0, scores.astype(jnp.float32)))
        return row, col

    rows, cols = jax.vmap(one)(data)
    if squeeze:
        return rows[0], cols[0]
    return rows, cols


# ---------------------------------------------------------------------------
# SSD: MultiBox family (reference: src/operator/contrib/multibox_*.cc)
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior")
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation; output (1, H*W*(S+R-1), 4) corner boxes in [0,1]
    units (reference: multibox_prior.cc)."""
    h, w = data.shape[-2:]
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)
    step_y = steps[1] if steps[1] > 0 else 1.0 / h
    step_x = steps[0] if steps[0] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[1]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[0]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cx.ravel(), cy.ravel()], axis=-1)  # (HW, 2)

    # anchor (w, h) combos: all sizes with ratio[0], then size[0] with
    # remaining ratios (reference order)
    whs = [(s, s) for s in sizes]
    s0 = sizes[0]
    for r in ratios[1:]:
        sr = np.sqrt(r)
        whs.append((s0 * sr, s0 / sr))
    wh = jnp.asarray(whs, jnp.float32)  # (A, 2)

    cxy = centers[:, None, :]  # (HW, 1, 2)
    half = wh[None, :, :] / 2  # (1, A, 2)
    boxes = jnp.concatenate([cxy - half, cxy + half], axis=-1)  # (HW, A, 4)
    boxes = boxes.reshape((-1, 4))
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes[None]


@register("_contrib_MultiBoxTarget")
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD target encoding (reference: multibox_target.cc).

    anchor (1, N, 4) corner; label (B, M, 5+) rows [cls, x1, y1, x2, y2];
    cls_pred (B, num_cls+1, N) (used for negative mining in the reference;
    hard-negative mining here keeps top-scoring negatives by max non-bg
    prob when negative_mining_ratio > 0).
    Returns [loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N)].
    """
    anchors = anchor[0]  # (N, 4)
    n = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)

    def one(lab, cpred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        ious = _pair_iou(anchors, gt_boxes)  # (N, M)
        ious = jnp.where(gt_valid[None, :], ious, -1.0)

        best_gt = jnp.argmax(ious, axis=1)           # per anchor
        best_iou = jnp.take_along_axis(ious, best_gt[:, None], 1)[:, 0]
        matched = best_iou >= overlap_threshold

        # stage 1: force-match the best anchor of each gt (reference
        # two-stage matching).  Invalid (padded) gt rows must not scatter:
        # route their writes to an out-of-range index (mode='drop'), else a
        # padded row colliding on anchor 0 clobbers a real match.
        best_anchor = jnp.argmax(ious, axis=0)       # per gt (M,)
        gt_usable = gt_valid & (jnp.max(ious, axis=0) > 1e-6)
        scatter_idx = jnp.where(gt_usable, best_anchor, n)
        forced = jnp.zeros((n,), bool)
        forced = forced.at[scatter_idx].set(True, mode="drop")
        best_gt = best_gt.at[scatter_idx].set(
            jnp.arange(lab.shape[0]), mode="drop")
        matched = matched | forced

        m_gt = gt_boxes[best_gt]  # (N, 4)
        # encode offsets (center form, variance-normalized)
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-8)
        ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-8)
        gcx = (m_gt[:, 0] + m_gt[:, 2]) / 2
        gcy = (m_gt[:, 1] + m_gt[:, 3]) / 2
        gw = jnp.maximum(m_gt[:, 2] - m_gt[:, 0], 1e-8)
        gh = jnp.maximum(m_gt[:, 3] - m_gt[:, 1], 1e-8)
        loc_t = jnp.stack([(gcx - acx) / aw / var[0],
                           (gcy - acy) / ah / var[1],
                           jnp.log(gw / aw) / var[2],
                           jnp.log(gh / ah) / var[3]], axis=-1)
        loc_target = jnp.where(matched[:, None], loc_t, 0.0).reshape(-1)
        loc_mask = jnp.where(matched[:, None],
                             jnp.ones((n, 4), jnp.float32), 0.0).reshape(-1)

        cls_t = jnp.where(matched, lab[best_gt, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            neg_score = jnp.max(cpred[1:, :], axis=0)  # max non-bg prob
            neg_cand = (~matched) & (neg_score > negative_mining_thresh)
            num_neg = jnp.maximum(
                (negative_mining_ratio * jnp.sum(matched)).astype(jnp.int32),
                minimum_negative_samples)
            order = jnp.argsort(-jnp.where(neg_cand, neg_score, -jnp.inf))
            rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n))
            keep_neg = neg_cand & (rank < num_neg)
            cls_t = jnp.where(~matched & ~keep_neg, ignore_label, cls_t)
        return loc_target, loc_mask, cls_t

    loc_target, loc_mask, cls_target = jax.vmap(one)(label, cls_pred)
    return loc_target, loc_mask, cls_target


def _decode_boxes(anchors, deltas, variances, clip_val=None):
    """Inverse of the multibox encoding: anchors (N,4) corner +
    variance-scaled deltas (N,4) -> corner boxes."""
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    cx = deltas[:, 0] * variances[0] * aw + acx
    cy = deltas[:, 1] * variances[1] * ah + acy
    w = jnp.exp(deltas[:, 2] * variances[2]) * aw
    h = jnp.exp(deltas[:, 3] * variances[3]) * ah
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    if clip_val is not None:
        boxes = jnp.clip(boxes, 0.0, clip_val)
    return boxes


@register("_contrib_MultiBoxDetection")
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD decode + per-class NMS (reference: multibox_detection.cc).
    cls_prob (B, C+1, N), loc_pred (B, N*4), anchor (1, N, 4)
    -> (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], invalid = -1."""
    anchors = anchor[0]
    n = anchors.shape[0]
    var = tuple(float(v) for v in variances)

    def one(cprob, lpred):
        boxes = _decode_boxes(anchors, lpred.reshape((n, 4)), var,
                              1.0 if clip else None)
        # best non-background class per anchor
        fg = jnp.concatenate([cprob[:background_id],
                              cprob[background_id + 1:]], axis=0)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        # account for removed background row
        cls_id = jnp.where(cls_id >= background_id, cls_id + 1, cls_id) - 1.0
        score = jnp.max(fg, axis=0)
        valid = score > threshold
        det = jnp.concatenate([
            jnp.where(valid, cls_id, -1.0)[:, None],
            jnp.where(valid, score, -1.0)[:, None], boxes], axis=-1)
        return det

    det = jax.vmap(one)(cls_prob, loc_pred)
    return box_nms(det, overlap_thresh=nms_threshold,
                   valid_thresh=0.0, topk=nms_topk,
                   coord_start=2, score_index=1, id_index=0,
                   background_id=-1, force_suppress=force_suppress)


# ---------------------------------------------------------------------------
# ROI ops (reference: src/operator/contrib/roi_align.*, src/operator/roi_pooling.*)
# ---------------------------------------------------------------------------
@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max ROI pooling; rois (R, 5) rows [batch_idx, x1, y1, x2, y2]."""
    return _roi_pool_impl(data, rois, tuple(pooled_size), spatial_scale,
                          mode="max")


@register("_contrib_ROIAlign")
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROIAlign with bilinear sampling (reference: roi_align.cc).
    TPU-native: a dense gather over a fixed sampling grid per output cell,
    vmapped over rois — no atomics (backward falls out of jax.vjp)."""
    ph, pw = tuple(int(p) for p in pooled_size)
    n, c, h, w = data.shape
    ratio = int(sample_ratio) if sample_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[1] * spatial_scale - offset,
                          roi[2] * spatial_scale - offset,
                          roi[3] * spatial_scale - offset,
                          roi[4] * spatial_scale - offset)
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sampling grid: (ph*ratio, pw*ratio) bilinear taps
        gy = y1 + (jnp.arange(ph * ratio, dtype=jnp.float32) + 0.5) * (
            bin_h / ratio)
        gx = x1 + (jnp.arange(pw * ratio, dtype=jnp.float32) + 0.5) * (
            bin_w / ratio)
        img = data[bidx]  # (C, H, W)

        def bilinear(y, x):
            y = jnp.clip(y, 0.0, h - 1.0)
            x = jnp.clip(x, 0.0, w - 1.0)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(x).astype(jnp.int32)
            y1i = jnp.minimum(y0 + 1, h - 1)
            x1i = jnp.minimum(x0 + 1, w - 1)
            wy = y - y0
            wx = x - x0
            v00 = img[:, y0, x0]
            v01 = img[:, y0, x1i]
            v10 = img[:, y1i, x0]
            v11 = img[:, y1i, x1i]
            return ((1 - wy) * (1 - wx) * v00 + (1 - wy) * wx * v01
                    + wy * (1 - wx) * v10 + wy * wx * v11)

        samples = jax.vmap(lambda y: jax.vmap(lambda x: bilinear(y, x))(gx))(gy)
        # (ph*ratio, pw*ratio, C) -> average pool ratio x ratio
        samples = samples.reshape(ph, ratio, pw, ratio, c)
        return samples.mean(axis=(1, 3)).transpose(2, 0, 1)  # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


def _roi_pool_impl(data, rois, pooled_size, spatial_scale, mode):
    ph, pw = pooled_size
    n, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        # clamp the ROI to the feature map (reference behavior) so no
        # pooling bin is ever empty
        x1 = jnp.clip(jnp.round(roi[1] * spatial_scale), 0, w - 1).astype(jnp.int32)
        y1 = jnp.clip(jnp.round(roi[2] * spatial_scale), 0, h - 1).astype(jnp.int32)
        x2 = jnp.clip(jnp.round(roi[3] * spatial_scale), 0, w - 1).astype(jnp.int32)
        y2 = jnp.clip(jnp.round(roi[4] * spatial_scale), 0, h - 1).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = data[bidx]
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def cell(iy, ix):
            cy1 = y1 + (iy * rh) // ph
            cy2 = y1 + ((iy + 1) * rh + ph - 1) // ph
            cx1 = x1 + (ix * rw) // pw
            cx2 = x1 + ((ix + 1) * rw + pw - 1) // pw
            mask = ((ys[:, None] >= cy1) & (ys[:, None] < cy2)
                    & (xs[None, :] >= cx1) & (xs[None, :] < cx2))
            vals = jnp.where(mask[None], img, -jnp.inf)
            m = jnp.max(vals, axis=(1, 2))
            return jnp.where(jnp.isfinite(m), m, 0.0)  # empty bin -> 0

        out = jax.vmap(lambda iy: jax.vmap(lambda ix: cell(iy, ix))(
            jnp.arange(pw)))(jnp.arange(ph))
        return out.transpose(2, 0, 1)  # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# RPN Proposal (reference: src/operator/contrib/proposal.cc,
# multi_proposal.cc)
# ---------------------------------------------------------------------------
def _generate_base_anchors(scales, ratios, stride):
    base = stride - 1.0
    cx = base / 2
    cy = base / 2
    anchors = []
    size = stride * stride
    for r in ratios:
        size_r = size / r
        ws = np.round(np.sqrt(size_r))
        hs = np.round(ws * r)
        for s in scales:
            w = ws * s
            h = hs * s
            anchors.append([cx - (w - 1) / 2, cy - (h - 1) / 2,
                            cx + (w - 1) / 2, cy + (h - 1) / 2])
    return np.asarray(anchors, np.float32)


@register("_contrib_Proposal")
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (reference: proposal.cc).
    cls_prob (B, 2A, H, W), bbox_pred (B, 4A, H, W), im_info (B, 3)
    -> rois (B*post_n, 5) [batch_idx, x1, y1, x2, y2] (+ scores)."""
    b, _, fh, fw = cls_prob.shape
    a = len(scales) * len(ratios)
    anchors = _rcnn_anchor_grid(scales, ratios, feature_stride, fh, fw)
    n = anchors.shape[0]
    pre_n = min(rpn_pre_nms_top_n, n) if rpn_pre_nms_top_n > 0 else n
    post_n = rpn_post_nms_top_n

    def one(cp, bp, info):
        scores = cp[a:].transpose(1, 2, 0).reshape(-1)  # fg scores (HWA,)
        deltas = bp.transpose(1, 2, 0).reshape(-1, 4)
        # decode (Faster-RCNN parameterization, variance 1)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw / 2
        acy = anchors[:, 1] + ah / 2
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], -1)
        im_h, im_w = info[0], info[1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1),
                           jnp.clip(boxes[:, 1], 0, im_h - 1),
                           jnp.clip(boxes[:, 2], 0, im_w - 1),
                           jnp.clip(boxes[:, 3], 0, im_h - 1)], -1)
        min_size = rpn_min_size * info[2]
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size)
                   & (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
        scores = jnp.where(keep_sz, scores, -1.0)
        # pre-NMS topk
        top_scores, order = jax.lax.top_k(scores, pre_n)
        top_boxes = boxes[order]
        keep = _greedy_nms_mask(top_boxes, top_scores,
                                top_scores > -1.0, threshold)
        rank = jnp.where(keep, jnp.arange(pre_n), pre_n + jnp.arange(pre_n))
        sel = jnp.argsort(rank)[:post_n]
        out_boxes = jnp.where(keep[sel][:, None], top_boxes[sel], 0.0)
        out_scores = jnp.where(keep[sel], top_scores[sel], 0.0)
        return out_boxes, out_scores

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(b, dtype=boxes.dtype), post_n)
    rois = jnp.concatenate([bidx[:, None], boxes.reshape(-1, 4)], axis=-1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


@register("_contrib_MultiProposal")
def multi_proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """Batch alias of Proposal (reference: multi_proposal.cc)."""
    return proposal(cls_prob, bbox_pred, im_info, **kwargs)


# ---------------------------------------------------------------------------
# box encode/decode (1.6-era contrib, used by GluonCV YOLO/SSD)
# ---------------------------------------------------------------------------
@register("_contrib_box_encode")
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched gt boxes against anchors (reference:
    bounding_box.cc BoxEncode). samples (B,N) {+1,-1,0}, matches (B,N) gt
    indices, anchors (B,N,4), refs (B,M,4) -> (targets (B,N,4), masks)."""
    means = jnp.asarray(means, jnp.float32)
    stds = jnp.asarray(stds, jnp.float32)

    def one(smp, mat, anc, ref):
        g = ref[mat.astype(jnp.int32)]
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-8)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        t = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                       jnp.log(gw / aw), jnp.log(gh / ah)], -1)
        t = (t - means) / stds
        mask = (smp > 0.5)[:, None]
        return jnp.where(mask, t, 0.0), mask.astype(t.dtype) * jnp.ones_like(t)

    t, m = jax.vmap(one)(samples, matches, anchors, refs)
    return t, m


@register("_contrib_box_decode")
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """Decode deltas back to boxes (reference: bounding_box.cc BoxDecode)."""
    stds = (std0, std1, std2, std3)

    def one(d):
        anc = _to_corner(anchors[0], format)
        deltas = d * jnp.asarray(stds, d.dtype)
        return _decode_boxes(anc, deltas, (1.0, 1.0, 1.0, 1.0),
                             clip if clip > 0 else None)

    return jax.vmap(one)(data)


@register("_contrib_mrcnn_mask_target", differentiable=False)
def mrcnn_mask_target(rois, gt_masks, matches, cls_targets, num_rois=None,
                      num_classes=1, mask_size=(14, 14), sample_ratio=2,
                      aligned=False):
    """Mask-RCNN training targets (reference: src/operator/contrib/
    mrcnn_mask_target.cu): crop each matched ground-truth mask to its ROI
    with bilinear ROIAlign sampling and emit per-class targets + weights.

    rois: (B, N, 4) [x1,y1,x2,y2] in image coords; gt_masks: (B, M, H, W)
    {0,1}; matches: (B, N) gt index per roi; cls_targets: (B, N) class id
    (0 = background).  Returns (mask_targets (B,N,C,ms,ms),
    mask_weights (B,N,C,ms,ms)) where weights one-hot the matched class.
    """
    if isinstance(mask_size, int):
        mask_size = (mask_size, mask_size)
    ms_h, ms_w = mask_size
    b, n, _ = rois.shape
    _, m, h, w = gt_masks.shape
    ratio = int(sample_ratio) if sample_ratio and sample_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0

    def one(roi, mask):
        # mask: (H, W) float; roi [x1,y1,x2,y2]
        x1, y1, x2, y2 = (roi[0] - offset, roi[1] - offset,
                          roi[2] - offset, roi[3] - offset)
        bin_w = jnp.maximum(x2 - x1, 1.0) / ms_w
        bin_h = jnp.maximum(y2 - y1, 1.0) / ms_h
        gy = y1 + (jnp.arange(ms_h * ratio, dtype=jnp.float32) + 0.5) * (
            bin_h / ratio)
        gx = x1 + (jnp.arange(ms_w * ratio, dtype=jnp.float32) + 0.5) * (
            bin_w / ratio)

        def bilinear(y, x):
            y = jnp.clip(y, 0.0, h - 1.0)
            x = jnp.clip(x, 0.0, w - 1.0)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(x).astype(jnp.int32)
            y1i = jnp.minimum(y0 + 1, h - 1)
            x1i = jnp.minimum(x0 + 1, w - 1)
            wy, wx = y - y0, x - x0
            return (mask[y0, x0] * (1 - wy) * (1 - wx)
                    + mask[y0, x1i] * (1 - wy) * wx
                    + mask[y1i, x0] * wy * (1 - wx)
                    + mask[y1i, x1i] * wy * wx)

        yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
        samples = jax.vmap(jax.vmap(bilinear))(yy, xx)
        return samples.reshape(ms_h, ratio, ms_w, ratio).mean(axis=(1, 3))

    def per_image(rois_i, masks_i, match_i):
        matched = masks_i[jnp.clip(match_i.astype(jnp.int32), 0, m - 1)]
        return jax.vmap(one)(rois_i, matched.astype(jnp.float32))

    targets = jax.vmap(per_image)(rois.astype(jnp.float32),
                                  gt_masks, matches)  # (B, N, ms, ms)
    cls = jnp.clip(cls_targets.astype(jnp.int32), 0, num_classes - 1)
    onehot = jax.nn.one_hot(cls, num_classes, dtype=targets.dtype)
    # weights zero for background (cls_target 0)
    onehot = onehot * (cls_targets > 0)[..., None].astype(targets.dtype)
    mask_targets = targets[:, :, None] * onehot[..., None, None]
    mask_weights = jnp.broadcast_to(
        onehot[..., None, None],
        (b, n, num_classes, ms_h, ms_w)).astype(targets.dtype)
    return mask_targets, mask_weights


# ---------------------------------------------------------------------------
# Faster-RCNN training targets (reference: example/rcnn anchor-target logic
# + src/operator/contrib/proposal_target.cc)
# ---------------------------------------------------------------------------
def _rcnn_anchor_grid(scales, ratios, stride, fh, fw):
    """Pixel-space anchor grid in the (H, W, A)-fastest-A layout shared
    with _contrib_Proposal -> (H*W*A, 4)."""
    base = _generate_base_anchors([float(s) for s in scales],
                                  [float(r) for r in ratios], float(stride))
    shift_x = jnp.arange(fw, dtype=jnp.float32) * stride
    shift_y = jnp.arange(fh, dtype=jnp.float32) * stride
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
    shifts = jnp.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()], -1)
    return (jnp.asarray(base)[None, :, :]
            + shifts[:, None, :]).reshape((-1, 4))


def _rcnn_encode(anchors, gt, stds=(1.0, 1.0, 1.0, 1.0)):
    """Inverse of _contrib_Proposal's decode (+1 pixel convention)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0] + 1.0, 1e-6)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1] + 1.0, 1e-6)
    gcx = gt[:, 0] + gw / 2
    gcy = gt[:, 1] + gh / 2
    t = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                   jnp.log(gw / aw), jnp.log(gh / ah)], -1)
    return t / jnp.asarray(stds, t.dtype)


@register("_contrib_RPNAnchorTarget", differentiable=False)
def rpn_anchor_target(cls_prob, gt_boxes, scales=(4.0, 8.0, 16.0, 32.0),
                      ratios=(0.5, 1.0, 2.0), feature_stride=16,
                      fg_overlap=0.7, bg_overlap=0.3):
    """RPN training targets (reference: example/rcnn AnchorLoader/assign_anchor
    ~L1-150, done there in numpy on the host per batch).

    TPU-native: runs inside the training program on device, so the whole
    Faster-RCNN step stays ONE XLA program.  Instead of the reference's
    random 256-anchor subsample (dynamic, host RNG), every anchor keeps its
    label and the LOSS normalizes fg/bg halves separately — the static,
    deterministic equivalent of a balanced minibatch.

    cls_prob: (B, 2A, H, W) — shape/layout donor for the anchor grid.
    gt_boxes: (B, M, 5) rows [cls, x1, y1, x2, y2] in pixels, cls<0 pads.
    Returns (labels (B, N) in {1 fg, 0 bg, -1 ignore},
             bbox_targets (B, N, 4), bbox_weights (B, N, 1)), N = H*W*A in
    the same (h, w, a) order as _contrib_Proposal.
    """
    b, c2a, fh, fw = cls_prob.shape
    anchors = _rcnn_anchor_grid(scales, ratios, feature_stride, fh, fw)
    assert anchors.shape[0] == (c2a // 2) * fh * fw, \
        f"anchor spec {anchors.shape[0]//(fh*fw)} != cls channels {c2a//2}"

    def one(gt):
        valid_gt = gt[:, 0] >= 0
        iou = _pair_iou(anchors, gt[:, 1:])              # (N, M)
        iou = jnp.where(valid_gt[None, :], iou, 0.0)
        max_iou = iou.max(axis=1)
        best_gt = iou.argmax(axis=1)
        # the best anchor of every gt is fg even below fg_overlap
        # (reference rule); tolerance for fp ties
        best_per_gt = iou.max(axis=0)
        is_best = ((iou >= best_per_gt[None, :] - 1e-6)
                   & valid_gt[None, :] & (iou > 0)).any(axis=1)
        fg = (max_iou >= fg_overlap) | is_best
        bg = (max_iou < bg_overlap) & ~fg
        labels = jnp.where(fg, 1.0, jnp.where(bg, 0.0, -1.0))
        t = _rcnn_encode(anchors, gt[best_gt, 1:])
        w = fg.astype(jnp.float32)[:, None]
        return labels, t * w, w

    return jax.vmap(one)(gt_boxes)


@register("_contrib_ProposalTarget", differentiable=False)
def proposal_target(rois, gt_boxes, num_classes=21, batch_images=1,
                    batch_rois=128, fg_fraction=0.25, fg_overlap=0.5,
                    box_stds=(0.1, 0.1, 0.2, 0.2)):
    """RCNN head training targets (reference: proposal_target.cc ~L1-250).

    Static-shape redesign: gt boxes join the candidate set (as upstream),
    matching is vectorized IoU, and the reference's RANDOM fg/bg subsample
    becomes a deterministic ranking — all fg by IoU desc, then bg by IoU
    desc (hardest negatives first) — truncated to batch_rois//batch_images
    per image.  fg_fraction caps the fg half like the reference.

    rois: (B*post, 5) [batch_idx, x1, y1, x2, y2] from _contrib_Proposal.
    gt_boxes: (B, M, 5) rows [cls, x1, y1, x2, y2], cls<0 pads (0-based
    foreground classes; output labels are 1-based, 0 = background).
    Returns (rois_out (batch_rois, 5), labels (batch_rois,),
             bbox_targets (batch_rois, 4*num_classes),
             bbox_weights (batch_rois, 4*num_classes));
    num_classes INCLUDES background (slot 0 never targeted).
    """
    b = int(batch_images)
    per_img = int(batch_rois) // b
    fg_quota = int(round(fg_fraction * per_img))
    rois_img = rois.reshape(b, -1, 5)

    def one(r, gt):
        valid_gt = gt[:, 0] >= 0
        cand = jnp.concatenate([r[:, 1:], gt[:, 1:]], axis=0)   # (P+M, 4)
        iou = jnp.where(valid_gt[None, :],
                        _pair_iou(cand, gt[:, 1:]), 0.0)        # (P+M, Mg)
        max_iou = iou.max(axis=1)
        best_gt = iou.argmax(axis=1)
        fg = max_iou >= fg_overlap
        # rank: fg by IoU desc, capped at fg_quota, then bg by IoU desc
        n = cand.shape[0]
        fg_rank = jnp.argsort(jnp.argsort(-jnp.where(fg, max_iou, -1.0)))
        bg_rank = jnp.argsort(jnp.argsort(-jnp.where(fg, -1.0, max_iou)))
        key = jnp.where(fg & (fg_rank < fg_quota), fg_rank, fg_quota + bg_rank)
        sel = jnp.argsort(key)[:per_img]
        # every SELECTED roi above fg_overlap keeps its fg label: when bg
        # candidates are scarce, over-quota fg can enter the batch, and
        # labeling a >=0.5-IoU roi "background" would be an actively wrong
        # signal (the reference drops unsampled fg; with static shapes the
        # honest equivalent is to let the fg fraction exceed the cap)
        sel_fg = fg[sel]
        labels = jnp.where(sel_fg, gt[best_gt[sel], 0] + 1.0, 0.0)
        t = _rcnn_encode(cand[sel], gt[best_gt[sel], 1:], box_stds)
        # scatter the 4 target values into the matched class's slot
        cls = labels.astype(jnp.int32)
        onehot = jax.nn.one_hot(cls, num_classes, dtype=t.dtype)  # (R, C)
        wt = (onehot * sel_fg[:, None]).repeat(4, axis=-1)        # (R, 4C)
        targets = (onehot[:, :, None] * t[:, None, :]).reshape(
            per_img, -1) * sel_fg[:, None]
        return cand[sel], labels, targets, wt

    out_rois, labels, targets, weights = jax.vmap(one)(rois_img, gt_boxes)
    bidx = jnp.repeat(jnp.arange(b, dtype=out_rois.dtype), per_img)
    rois_out = jnp.concatenate(
        [bidx[:, None], out_rois.reshape(-1, 4)], axis=-1)
    return (rois_out, labels.reshape(-1), targets.reshape(batch_rois, -1),
            weights.reshape(batch_rois, -1))
