"""Fused-kernel registry: Pallas kernels keyed by op-class and platform.

The TPP argument (arXiv:2104.05755) applied to this repo's dispatch
layer: each entry maps a REGISTERED OP NAME (the op-class) to a Pallas
kernel with the same calling convention, tagged with the platforms it
may substitute on.  The ``fused_kernels`` graph pass
(passes/builtin.FusedKernelPass) consults :func:`substitution` from the
traced branch of ``ops/registry._invoke_impl`` and swaps the op's
FCompute in — so fusion is a PASS decision with a fingerprint, not an
if-ladder inside each op.

Platform resolution follows ``use_compiled()``'s single source of truth:
the ``compute_on`` override wins over the process default backend, and
kernels picked on a non-TPU platform run in interpret mode (the CPU test
path, forced by MX_PALLAS_FUSED=1).

Catalog: the existing fused kernels (layer_norm, flash_attention) plus
the new fused residual-add + LayerNorm block (``add_layer_norm``).
``paged_decode_attention`` stays engine-internal — it is not an op-class
(the serving engine composes it directly, gated by MX_SERVE_FLASH).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

__all__ = ["register_kernel", "registered_ops", "substitution",
           "KernelEntry"]


class KernelEntry:
    __slots__ = ("op_name", "platforms", "fn")

    def __init__(self, op_name: str, platforms: Tuple[str, ...],
                 fn: Callable):
        self.op_name = op_name
        self.platforms = tuple(platforms)
        self.fn = fn


_KERNELS: Dict[str, KernelEntry] = {}


def register_kernel(op_name: str, platforms: Tuple[str, ...] = ("cpu", "tpu")):
    """Decorator: register ``fn`` as the fused substitute for
    ``op_name`` on ``platforms``.  The fn must match the op's calling
    convention exactly (same positional arrays, same attrs) — the pass
    swaps it in blind."""

    def deco(fn: Callable) -> Callable:
        from ...base import MXNetError

        if op_name in _KERNELS:
            raise MXNetError(
                f"fused kernel for op {op_name!r} registered twice")
        _KERNELS[op_name] = KernelEntry(op_name, platforms, fn)
        return fn

    return deco


def registered_ops():
    return sorted(_KERNELS)


def _current_platform() -> str:
    import jax

    from . import _platform_override

    return _platform_override.get() or jax.default_backend()


def substitution(op_name: str,
                 platform: Optional[str] = None) -> Optional[Callable]:
    """The kernel to substitute for ``op_name`` on ``platform`` (default:
    the platform the current trace targets), or None."""
    entry = _KERNELS.get(op_name)
    if entry is None:
        return None
    plat = platform if platform is not None else _current_platform()
    return entry.fn if plat in entry.platforms else None


# ---------------------------------------------------------------------------
# catalog
# ---------------------------------------------------------------------------
@register_kernel("LayerNorm")
def _layer_norm_sub(data, gamma, beta, axis=-1, eps=1e-5,
                    output_mean_var=False):
    # the kernel is row-wise over the last axis; other attr combos keep
    # the stock implementation (which returns mean/var, handles any axis)
    if output_mean_var or axis not in (-1, data.ndim - 1) or data.ndim < 2:
        from ..registry import get_op

        return get_op("LayerNorm").fn(data, gamma, beta, axis=axis, eps=eps,
                                      output_mean_var=output_mean_var)
    from . import layer_norm

    out = layer_norm(data.reshape(-1, data.shape[-1]), gamma, beta, eps=eps)
    return out.reshape(data.shape)


@register_kernel("_contrib_add_layer_norm")
def _add_layer_norm_sub(data, residual, gamma, beta, eps=1e-5):
    from .fused import add_layer_norm

    c = data.shape[-1]
    out = add_layer_norm(data.reshape(-1, c), residual.reshape(-1, c),
                         gamma, beta, eps=eps)
    return out.reshape(data.shape)


@register_kernel("_contrib_flash_attention")
def _flash_attention_sub(q, k, v, causal=False, sm_scale=None):
    import math

    from ...parallel import ring_scope

    if ring_scope() is not None:
        # an active sequence-parallel scope owns attention routing —
        # defer to the stock op (ring/ulysses kernels over ppermute)
        from ..registry import get_op

        return get_op("_contrib_flash_attention").fn(
            q, k, v, causal=causal, sm_scale=sm_scale)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    from . import flash_attention

    return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
