"""FlashAttention-2 in Pallas (TPU).

Blockwise online-softmax attention: never materialises the (Lq, Lk) score
matrix in HBM.  Forward keeps a running (max, sum, acc) per q row; backward
is the standard two-kernel FA2 scheme (dq sweep over k blocks; dk/dv sweep
over q blocks) using the saved logsumexp.

Reference parity: supersedes src/operator/contrib/transformer.cc
(interleaved_matmul_selfatt_qk/valatt ~L1-300), which fused only the
attention matmuls and still materialised scores for a separate softmax op.

Shapes: q (N, Lq, D), k/v (N, Lk, D) with N = batch*heads; 4D
(B, H, L, D) inputs are reshaped.  Compute is f32 on the MXU regardless of
input dtype (bf16 inputs stay bf16 in HBM/VMEM).
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30
_LANES = 128  # TPU lane width: per-row stats (lse/delta) carry a trailing
              # 128-lane dim so their blocks satisfy Mosaic tiling rules
              # (same trick as jax's in-tree flash kernel, MIN_BLOCK_SIZE)


class _Cfg(NamedTuple):
    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    q_len: int     # unpadded
    kv_len: int    # unpadded
    interpret: bool


def _interpret() -> bool:
    from . import use_compiled

    return not use_compiled()


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _pick_block(length: int, preferred: int) -> int:
    if length >= preferred:
        return preferred
    return _round_up(length, 8)


def _kv_mask(cfg: _Cfg, qi, kj, bq, bk):
    """Validity mask for a (bq, bk) score tile at q block qi / k block kj."""
    kpos = kj * cfg.block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = kpos < cfg.kv_len
    if cfg.causal:
        qpos = qi * cfg.block_q + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        mask = jnp.logical_and(mask, qpos >= kpos)
    return mask


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(cfg: _Cfg, q_ref, k_ref, v_ref, o_ref, lse_ref):
    qi = pl.program_id(1)
    bq, bk = cfg.block_q, cfg.block_k
    q = q_ref[0].astype(jnp.float32) * cfg.sm_scale          # (bq, D)
    nkb = k_ref.shape[1] // bk

    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kj * bk, bk), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = jnp.where(_kv_mask(cfg, qi, kj, bq, bk), s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, a0))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(safe_l), (bq, _LANES))


def _fwd(cfg: _Cfg, q, k, v):
    n, lq, d = q.shape
    lk = k.shape[1]
    nqb = lq // cfg.block_q
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg),
        grid=(n, nqb),
        in_specs=[
            pl.BlockSpec((1, cfg.block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, cfg.block_q, _LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, lq, d), q.dtype),
            jax.ShapeDtypeStruct((n, lq, _LANES), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(q, k, v)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward: dq kernel (parallel over q blocks), dkv kernel (over k blocks)
# ---------------------------------------------------------------------------
def _dq_kernel(cfg: _Cfg, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref):
    qi = pl.program_id(1)
    bq, bk = cfg.block_q, cfg.block_k
    q = q_ref[0].astype(jnp.float32) * cfg.sm_scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0:1]
    delta = delta_ref[0, :, 0:1]
    nkb = k_ref.shape[1] // bk
    dq0 = jnp.zeros_like(q)

    def body(kj, dq):
        k = k_ref[0, pl.ds(kj * bk, bk), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = jnp.where(_kv_mask(cfg, qi, kj, bq, bk), s, _NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nkb, body, dq0)
    dq_ref[0] = (dq * cfg.sm_scale).astype(dq_ref.dtype)


def _dkv_kernel(cfg: _Cfg, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref):
    kj = pl.program_id(1)
    bq, bk = cfg.block_q, cfg.block_k
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    nqb = q_ref.shape[1] // bq
    dk0 = jnp.zeros_like(k)
    dv0 = jnp.zeros_like(v)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * bq, bq), :].astype(jnp.float32) * cfg.sm_scale
        do = do_ref[0, pl.ds(qi * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * bq, bq), 0:1]
        delta = delta_ref[0, pl.ds(qi * bq, bq), 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = jnp.where(_kv_mask(cfg, qi, kj, bq, bk), s, _NEG)
        p = jnp.exp(s - lse)                                   # (bq, bk)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, nqb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_impl(cfg: _Cfg, q, k, v, out, lse, do):
    n, lq, d = q.shape
    lk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                   # (n, lq)
    lse3 = jnp.broadcast_to(lse[..., None], (n, lq, _LANES))
    delta3 = jnp.broadcast_to(delta[..., None], (n, lq, _LANES))
    common = [
        pl.BlockSpec((1, lq, d), lambda b, i: (b, 0, 0)),      # q
        pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),      # k
        pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),      # v
        pl.BlockSpec((1, lq, d), lambda b, i: (b, 0, 0)),      # do
        pl.BlockSpec((1, lq, _LANES), lambda b, i: (b, 0, 0)),   # lse
        pl.BlockSpec((1, lq, _LANES), lambda b, i: (b, 0, 0)),   # delta
    ]
    dq_specs = list(common)
    dq_specs[0] = pl.BlockSpec((1, cfg.block_q, d), lambda b, i: (b, i, 0))
    dq_specs[3] = pl.BlockSpec((1, cfg.block_q, d), lambda b, i: (b, i, 0))
    dq_specs[4] = pl.BlockSpec((1, cfg.block_q, _LANES),
                               lambda b, i: (b, i, 0))
    dq_specs[5] = pl.BlockSpec((1, cfg.block_q, _LANES),
                               lambda b, i: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg),
        grid=(n, lq // cfg.block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, cfg.block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, lq, d), q.dtype),
        interpret=cfg.interpret,
    )(q, k, v, do, lse3, delta3)

    dkv_specs = list(common)
    dkv_specs[1] = pl.BlockSpec((1, cfg.block_k, d), lambda b, j: (b, j, 0))
    dkv_specs[2] = pl.BlockSpec((1, cfg.block_k, d), lambda b, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg),
        grid=(n, lk // cfg.block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, cfg.block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, cfg.block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, lk, d), k.dtype),
            jax.ShapeDtypeStruct((n, lk, d), v.dtype),
        ],
        interpret=cfg.interpret,
    )(q, k, v, do, lse3, delta3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, q, k, v):
    out, _ = _fwd(cfg, q, k, v)
    return out


def _flash_fwd(cfg: _Cfg, q, k, v):
    out, lse = _fwd(cfg, q, k, v)
    return out, (q, k, v, out, lse)


def _flash_bwd(cfg: _Cfg, res, do):
    q, k, v, out, lse = res
    return _bwd_impl(cfg, q, k, v, out, lse, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    return_lse: bool = False):
    """Fused attention: softmax(q @ k^T * sm_scale [+ causal mask]) @ v.

    q: (N, Lq, D) or (B, H, Lq, D); k, v likewise with Lk.  Differentiable
    in q/k/v (FA2 backward).  `return_lse` additionally returns the row
    logsumexp (N, Lq) in f32 (not differentiable; used by ring attention).
    """
    q4 = q.ndim == 4
    if q4:
        b, h = q.shape[:2]
        q = q.reshape(b * h, *q.shape[2:])
        k = k.reshape(b * h, *k.shape[2:])
        v = v.reshape(b * h, *v.shape[2:])
    n, lq, d = q.shape
    lk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    bq = _pick_block(lq, block_q)
    bk = _pick_block(lk, block_k)
    lq_p, lk_p = _round_up(lq, bq), _round_up(lk, bk)
    cfg = _Cfg(bool(causal), float(sm_scale), bq, bk, lq, lk, _interpret())
    pad = lambda x, L: jnp.pad(x, ((0, 0), (0, L - x.shape[1]), (0, 0)))
    qp, kp, vp = pad(q, lq_p), pad(k, lk_p), pad(v, lk_p)
    if return_lse:
        out, lse = _fwd(cfg, qp, kp, vp)
        out, lse = out[:, :lq], lse[:, :lq]
    else:
        out = _flash(cfg, qp, kp, vp)[:, :lq]
        lse = None
    if q4:
        out = out.reshape(b, h, lq, d)
        if lse is not None:
            lse = lse.reshape(b, h, lq)
    return (out, lse) if return_lse else out
