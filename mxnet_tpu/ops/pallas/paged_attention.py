"""Ragged paged decode attention in Pallas (TPU).

The fused path of the serving engine's paged KV cache
(mxnet_tpu/serving/paged_cache.py; design per *Ragged Paged Attention*,
PAPERS.md arxiv 2604.15464): ONE query per sequence slot attends over
that slot's page-table-addressed KV pages with per-slot length masking —
the dense ``(S, Lmax, C)`` gathered view is never materialised.  Online
softmax (running max / sum / accumulator per head) over the page loop,
exactly the flash_attention recurrence with pages as the k blocks and the
slot's *own* ragged length as the mask, so mixed-length in-flight
requests share one kernel instance.

Forward-only (decode is inference; no vjp).  Compute is f32 regardless
of pool dtype.  Like the other kernels in this package it runs in
interpret mode off-TPU (the CPU test path) and lowers through Mosaic on
TPU.  The page table and lengths are scalar-prefetch operands
(``PrefetchScalarGridSpec``): resident in SMEM before the body runs, so
the page loop can read pool rows by dynamic index.

Shapes: q (S, H, hd); k_pool/v_pool (N, page_size, H, hd);
page_table (S, P) int32; lengths (S,) int32 (valid cache rows per slot,
0 = slot inactive -> zero output).  Returns (S, H, hd) in q's dtype.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30

__all__ = ["paged_decode_attention"]


def _kernel(ps: int, P: int, sm_scale: float,
            table_ref, len_ref, q_ref, kpool_ref, vpool_ref, o_ref):
    s = pl.program_id(0)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (H, hd)
    H, hd = q.shape
    length = len_ref[s]

    m0 = jnp.full((H, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((H, 1), jnp.float32)
    a0 = jnp.zeros((H, hd), jnp.float32)

    def body(p, carry):
        m, l, acc = carry
        page = table_ref[s * P + p]
        k = kpool_ref[pl.ds(page, 1)][0].astype(jnp.float32)  # (ps, H, hd)
        v = vpool_ref[pl.ds(page, 1)][0].astype(jnp.float32)
        # (H, ps) scores: batched over heads — q (H, hd) x k^T (H, hd, ps)
        kt = jnp.transpose(k, (1, 2, 0))                      # (H, hd, ps)
        scores = jax.lax.dot_general(
            q, kt, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)               # (H, ps)
        kpos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (H, ps), 1)
        scores = jnp.where(kpos < length, scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        prob = jnp.exp(scores - m_new)                        # (H, ps)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + prob.sum(axis=-1, keepdims=True)
        vt = jnp.transpose(v, (1, 0, 2))                      # (H, ps, hd)
        pv = jax.lax.dot_general(
            prob, vt, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)               # (H, hd)
        return m_new, l, acc * alpha + pv

    m, l, acc = jax.lax.fori_loop(0, P, body, (m0, l0, a0))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    # length == 0 (inactive slot): every score masked -> uniform probs
    # would leak pool garbage; force the output to zero instead
    out = jnp.where(length > 0, acc / safe_l, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, page_table, lengths,
                           sm_scale=None):
    """softmax(q @ K_pages^T * sm_scale) @ V_pages per slot, masked to
    each slot's own ``lengths`` — see the module docstring for shapes."""
    from . import use_compiled
    from jax.experimental.pallas import tpu as pltpu

    S, H, hd = q.shape
    N, ps, _, _ = k_pool.shape
    P = page_table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    # index maps receive the scalar-prefetch refs after the grid indices
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda s, *_: (s, 0, 0)),         # q
            pl.BlockSpec((N, ps, H, hd), lambda s, *_: (0, 0, 0, 0)),  # k
            pl.BlockSpec((N, ps, H, hd), lambda s, *_: (0, 0, 0, 0)),  # v
        ],
        out_specs=pl.BlockSpec((1, H, hd), lambda s, *_: (s, 0, 0)),
    )
    call = pl.pallas_call(
        functools.partial(_kernel, ps, P, float(sm_scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, hd), q.dtype),
        interpret=not use_compiled(),
    )
    return call(page_table.reshape(-1).astype(jnp.int32),
                lengths.astype(jnp.int32), q, k_pool, v_pool)
