"""Pallas TPU kernels for the hot fused ops.

Reference parity: this package is the TPU-native replacement for the
reference's hand-written device kernels and RTC fusion:
  * src/operator/contrib/transformer.cc (interleaved_matmul_selfatt_qk /
    valatt, ~L1-300) -> flash_attention (blockwise online-softmax attention,
    a strictly stronger fusion than the reference's matmul-only fusion);
  * src/operator/nn/softmax{-inl.h,.cc,.cu} fused softmax+CE grad ->
    softmax_cross_entropy;
  * src/operator/nn/layer_norm* -> layer_norm;
  * src/operator/fusion/fused_op.cc (NVRTC pointwise fusion, env
    MXNET_USE_FUSION ~L100) -> the `enabled()` gate below: XLA already
    fuses pointwise chains, so only the blockwise kernels live here.

All kernels run in interpret mode on CPU (so the test suite exercises them
on the 8-device virtual mesh) and compile through Mosaic on TPU.
"""
from .flash_attention import flash_attention
from .fused import add_layer_norm, layer_norm, softmax_cross_entropy
from .paged_attention import paged_decode_attention

import os


def enabled() -> bool:
    """MXNET_USE_FUSION gate (default on), reference env-var semantics."""
    return os.environ.get("MXNET_USE_FUSION", "1") not in ("0", "false")


from contextlib import contextmanager
from contextvars import ContextVar

# per-context so concurrent steps on meshes of different platforms can't
# bake each other's interpret flag into a traced kernel
_platform_override: ContextVar = ContextVar("pallas_platform", default=None)


def use_compiled() -> bool:
    """True when Pallas kernels should lower through Mosaic (TPU backend).

    Single source of truth for call-site gates: kernels run interpreted
    exactly when this is False, so a gate that checks `enabled() and
    use_compiled()` can never disagree with the kernels' interpret flag.

    Keyed off the platform the computation will actually run on — an
    explicit `compute_on(...)` override (set by DataParallelStep/dryrun
    when jitting over a mesh) wins over the process default backend, so a
    CPU mesh under a TPU default backend correctly gets interpret mode.
    """
    import jax

    platform = _platform_override.get() or jax.default_backend()
    return platform == "tpu"


@contextmanager
def compute_on(platform: str):
    """Scope within which Pallas kernels lower for `platform` ('cpu'/'tpu').

    Used at trace time (the interpret flag is baked into pallas_call when
    the enclosing jit traces)."""
    token = _platform_override.set(platform)
    try:
        yield
    finally:
        _platform_override.reset(token)


__all__ = ["flash_attention", "softmax_cross_entropy", "layer_norm",
           "add_layer_norm", "paged_decode_attention", "enabled",
           "use_compiled", "compute_on", "registry"]

# the fused-kernel registry (op-class -> Pallas kernel, per platform);
# imported last: its catalog references the kernels above
from . import registry  # noqa: E402
