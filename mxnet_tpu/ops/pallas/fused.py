"""Fused row-wise Pallas kernels: softmax cross-entropy and layer norm.

Reference parity:
  * softmax_cross_entropy: src/operator/nn/softmax{-inl.h,.cc,.cu} fused
    log-softmax + gather (the reference fuses softmax with its grad; here
    the whole loss row reduces in one VMEM pass);
  * layer_norm: src/operator/nn/layer_norm* (Welford pass + affine in one
    kernel).

Backward passes are closed-form jnp expressions under jax.custom_vjp —
XLA fuses those chains on its own; the win of Pallas is the forward
single-pass reduction without materialising intermediates.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    from . import use_compiled

    return not use_compiled()


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# softmax cross-entropy
# ---------------------------------------------------------------------------
def _sce_kernel(ignore_label, x_ref, y_ref, loss_ref):
    x = x_ref[...].astype(jnp.float32)            # (bn, C)
    y = y_ref[...]                                # (bn, 1) int32
    m = x.max(axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    lse = m[:, 0] + jnp.log(e.sum(axis=-1))
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.where(cols == y, x, 0.0).sum(axis=-1)
    loss = lse - picked
    if ignore_label is not None:
        loss = jnp.where(y[:, 0] == ignore_label, 0.0, loss)
    loss_ref[...] = loss[:, None]


def _sce_fwd_impl(logits, labels, ignore_label):
    n, c = logits.shape
    bn = min(256, _round_up(n, 8))
    n_p = _round_up(n, bn)
    x = jnp.pad(logits, ((0, n_p - n), (0, 0)))
    y = jnp.pad(labels.astype(jnp.int32), ((0, n_p - n),))[:, None]
    loss = pl.pallas_call(
        functools.partial(_sce_kernel, ignore_label),
        grid=(n_p // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_p, 1), jnp.float32),
        interpret=_interpret(),
    )(x, y)
    return loss[:n, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy(logits, labels, ignore_label=None):
    """Per-row -log softmax(logits)[label]; logits (N, C), labels (N,) int.

    Rows whose label equals `ignore_label` contribute zero loss/grad.
    """
    return _sce_fwd_impl(logits, labels, ignore_label)


def _sce_fwd(logits, labels, ignore_label):
    return _sce_fwd_impl(logits, labels, ignore_label), (logits, labels)


def _sce_bwd(ignore_label, res, g):
    logits, labels = res
    x = logits.astype(jnp.float32)
    p = jax.nn.softmax(x, axis=-1)
    onehot = jax.nn.one_hot(labels, x.shape[-1], dtype=jnp.float32)
    d = (p - onehot) * g[:, None]
    if ignore_label is not None:
        d = jnp.where((labels == ignore_label)[:, None], 0.0, d)
    return d.astype(logits.dtype), None


softmax_cross_entropy.defvjp(_sce_fwd, _sce_bwd)


# ---------------------------------------------------------------------------
# layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(eps, x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref):
    x = x_ref[...].astype(jnp.float32)            # (bn, C)
    mu = x.mean(axis=-1, keepdims=True)
    xc = x - mu
    var = (xc * xc).mean(axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o = xc * rstd * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    o_ref[...] = o.astype(o_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _ln_fwd_impl(x, gamma, beta, eps):
    n, c = x.shape
    bn = min(256, _round_up(n, 8))
    n_p = _round_up(n, bn)
    xp = jnp.pad(x, ((0, n_p - n), (0, 0)))
    out, mu, rstd = pl.pallas_call(
        functools.partial(_ln_kernel, eps),
        grid=(n_p // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_p, c), x.dtype),
                   jax.ShapeDtypeStruct((n_p, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n_p, 1), jnp.float32)],
        interpret=_interpret(),
    )(xp, gamma[None, :], beta[None, :])
    return out[:n], mu[:n], rstd[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, gamma, beta, eps=1e-5):
    """Row-wise layer norm over the last axis; x (N, C), gamma/beta (C,)."""
    out, _, _ = _ln_fwd_impl(x, gamma, beta, eps)
    return out


def _ln_fwd(x, gamma, beta, eps):
    out, mu, rstd = _ln_fwd_impl(x, gamma, beta, eps)
    return out, (x, gamma, mu, rstd)


def _ln_bwd(eps, res, g):
    x, gamma, mu, rstd = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    xhat = (xf - mu) * rstd
    dgamma = (gf * xhat).sum(axis=0)
    dbeta = gf.sum(axis=0)
    dxhat = gf * gamma.astype(jnp.float32)[None, :]
    c = x.shape[-1]
    dx = rstd / c * (c * dxhat - dxhat.sum(-1, keepdims=True)
                     - xhat * (dxhat * xhat).sum(-1, keepdims=True))
    return dx.astype(x.dtype), dgamma.astype(gamma.dtype), dbeta.astype(
        gamma.dtype)


layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# fused residual-add + layer norm
# ---------------------------------------------------------------------------
def _aln_kernel(eps, x_ref, r_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref):
    x = (x_ref[...].astype(jnp.float32)
         + r_ref[...].astype(jnp.float32))     # (bn, C): the fused add
    mu = x.mean(axis=-1, keepdims=True)
    xc = x - mu
    var = (xc * xc).mean(axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o = xc * rstd * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    o_ref[...] = o.astype(o_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _aln_fwd_impl(x, res, gamma, beta, eps):
    n, c = x.shape
    bn = min(256, _round_up(n, 8))
    n_p = _round_up(n, bn)
    xp = jnp.pad(x, ((0, n_p - n), (0, 0)))
    rp = jnp.pad(res, ((0, n_p - n), (0, 0)))
    out, mu, rstd = pl.pallas_call(
        functools.partial(_aln_kernel, eps),
        grid=(n_p // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_p, c), x.dtype),
                   jax.ShapeDtypeStruct((n_p, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n_p, 1), jnp.float32)],
        interpret=_interpret(),
    )(xp, rp, gamma[None, :], beta[None, :])
    return out[:n], mu[:n], rstd[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def add_layer_norm(x, res, gamma, beta, eps=1e-5):
    """Fused residual add + row-wise layer norm: LN(x + res) in ONE VMEM
    pass — the pre-norm transformer block boundary never materialises
    the sum.  x/res (N, C), gamma/beta (C,)."""
    out, _, _ = _aln_fwd_impl(x, res, gamma, beta, eps)
    return out


def _aln_fwd(x, res, gamma, beta, eps):
    out, mu, rstd = _aln_fwd_impl(x, res, gamma, beta, eps)
    return out, (x, res, gamma, mu, rstd)


def _aln_bwd(eps, resids, g):
    x, res, gamma, mu, rstd = resids
    s = x.astype(jnp.float32) + res.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    xhat = (s - mu) * rstd
    dgamma = (gf * xhat).sum(axis=0)
    dbeta = gf.sum(axis=0)
    dxhat = gf * gamma.astype(jnp.float32)[None, :]
    c = x.shape[-1]
    ds = rstd / c * (c * dxhat - dxhat.sum(-1, keepdims=True)
                     - xhat * (dxhat * xhat).sum(-1, keepdims=True))
    # the add fans the cotangent out to BOTH branches unchanged
    return (ds.astype(x.dtype), ds.astype(res.dtype),
            dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


add_layer_norm.defvjp(_aln_fwd, _aln_bwd)
