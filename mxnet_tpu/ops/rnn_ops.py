"""Fused multi-layer RNN (reference: src/operator/rnn.{cc,-inl.h} +
cudnn_rnn-inl.h / MIOpen RNN).

TPU-native: the recurrence is a lax.scan per layer/direction — XLA compiles
the whole stack into one looped kernel (compiler-friendly control flow; no
unrolled graph blowup), the TPU analog of the vendor fused RNN.  Gate
orderings follow the cuDNN/MXNet convention: LSTM [i, f, g, o], GRU [r, z, n].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _step_rnn_relu(x_t, h, wi, wh, bi, bh):
    return jnp.maximum(x_t @ wi.T + bi + h @ wh.T + bh, 0)


def _step_rnn_tanh(x_t, h, wi, wh, bi, bh):
    return jnp.tanh(x_t @ wi.T + bi + h @ wh.T + bh)


def _step_lstm(x_t, h, c, wi, wh, bi, bh):
    gates = x_t @ wi.T + bi + h @ wh.T + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    new_c = f * c + i * g
    new_h = o * jnp.tanh(new_c)
    return new_h, new_c

def _step_gru(x_t, h, wi, wh, bi, bh):
    gi = x_t @ wi.T + bi
    gh = h @ wh.T + bh
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return (1 - z) * n + z * h


def _scan_layer(mode, xs, h0, c0, wi, wh, bi, bh, reverse=False):
    """Run one direction of one layer over time; xs: (T, B, I)."""

    if mode == "lstm":
        def body(carry, x_t):
            h, c = carry
            new_h, new_c = _step_lstm(x_t, h, c, wi, wh, bi, bh)
            return (new_h, new_c), new_h

        (hT, cT), ys = jax.lax.scan(body, (h0, c0), xs, reverse=reverse)
        return ys, hT, cT

    step = {"rnn_relu": _step_rnn_relu, "rnn_tanh": _step_rnn_tanh,
            "gru": _step_gru}[mode]

    def body(h, x_t):
        new_h = step(x_t, h, wi, wh, bi, bh)
        return new_h, new_h

    hT, ys = jax.lax.scan(body, h0, xs, reverse=reverse)
    return ys, hT, None


@register("_fused_rnn")
def _fused_rnn(data, key, state_h, state_c, *weights, mode="lstm",
               state_size=0, num_layers=1, bidirectional=False, p=0.0,
               training=False, state_outputs=True):
    """Multi-layer (bi)directional RNN over TNC data.

    weights: per layer, per direction: i2h_w, h2h_w, i2h_b, h2h_b.
    state_h/state_c: (num_layers*dirs, B, H).  Returns (out, h_n[, c_n]).
    """
    dirs = 2 if bidirectional else 1
    xs = data
    idx = 0
    h_out, c_out = [], []
    keys = (jax.random.split(key, num_layers)
            if (training and p > 0.0) else [None] * num_layers)
    for layer in range(num_layers):
        layer_outs = []
        for d in range(2 if bidirectional else 1):
            wi, wh, bi, bh = weights[idx * 4: idx * 4 + 4]
            s = layer * dirs + d
            h0 = state_h[s]
            c0 = state_c[s] if mode == "lstm" else None
            ys, hT, cT = _scan_layer(mode, xs, h0, c0, wi, wh, bi, bh,
                                     reverse=(d == 1))
            layer_outs.append(ys)
            h_out.append(hT)
            if mode == "lstm":
                c_out.append(cT)
            idx += 1
        xs = (jnp.concatenate(layer_outs, axis=-1) if bidirectional
              else layer_outs[0])
        if training and p > 0.0 and layer < num_layers - 1:
            keep = 1.0 - p
            mask = jax.random.bernoulli(keys[layer], keep, xs.shape)
            xs = xs * mask.astype(xs.dtype) / keep
    h_n = jnp.stack(h_out, axis=0)
    if mode == "lstm":
        return xs, h_n, jnp.stack(c_out, axis=0)
    return xs, h_n


@register("_begin_state_zeros", differentiable=False)
def _begin_state_zeros(data, num_hidden=0, batch_axis=0):
    """Zero initial state (B, H) derived from an input's batch dim — the
    TPU-native replacement for the reference's shape-(0,H) deferred zeros
    (mx.rnn BaseRNNCell.begin_state)."""
    return jnp.zeros((data.shape[int(batch_axis)], int(num_hidden)),
                     data.dtype)


@register("_begin_state_zeros_layers", differentiable=False)
def _begin_state_zeros_layers(data, num_hidden=0, num_layers=1,
                              batch_axis=1):
    """Zero initial state (L, B, H); batch_axis selects the batch dim of
    the input (1 for a merged TNC tensor, 0 for a (B, C) step slice)."""
    return jnp.zeros((int(num_layers), data.shape[int(batch_axis)],
                      int(num_hidden)), data.dtype)


def rnn_packed_layout(mode, input_size, state_size, num_layers,
                      bidirectional):
    """Single source of truth for the packed flat RNN parameter vector
    (reference rnn-inl.h GetRnnParamSize: weights layer/direction-major,
    i2h then h2h, followed by all biases in the same order).

    Returns (entries, total) where entries are
    (layer, direction, group 'i2h'|'h2h', kind 'weight'|'bias',
    offset, shape).  Consumed by the RNN op, symbolic shape inference,
    and mx.rnn.FusedRNNCell pack/unpack.
    """
    gates = {"lstm": 4, "gru": 3, "rnn_relu": 1, "rnn_tanh": 1}[mode]
    H = int(state_size)
    dirs = 2 if bidirectional else 1
    entries = []
    off = 0
    for layer in range(int(num_layers)):
        inp = int(input_size) if layer == 0 else H * dirs
        for d in range(dirs):
            entries.append((layer, d, "i2h", "weight", off, (gates * H, inp)))
            off += gates * H * inp
            entries.append((layer, d, "h2h", "weight", off, (gates * H, H)))
            off += gates * H * H
    for layer in range(int(num_layers)):
        for d in range(dirs):
            entries.append((layer, d, "i2h", "bias", off, (gates * H,)))
            off += gates * H
            entries.append((layer, d, "h2h", "bias", off, (gates * H,)))
            off += gates * H
    return entries, off
