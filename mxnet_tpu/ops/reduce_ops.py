"""Reduction and ordering ops.

Reference parity: src/operator/tensor/broadcast_reduce_op_value.* (sum, mean,
norm, ...), ordering_op.* (topk/sort/argsort via CUB→hipCUB).  XLA lowers
reductions to tiled VPU code; sorting uses XLA's variadic sort.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _axis(axis):
    """MXNet axis attr: None/int/tuple; () means all axes in 1.x."""
    if axis is None or axis == ():
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


def _reduce(fn, x, axis=None, keepdims=False, exclude=False):
    ax = _axis(axis)
    if exclude and ax is not None:
        if isinstance(ax, int):
            ax = (ax,)
        ax = tuple(i for i in range(x.ndim) if i not in tuple(a % x.ndim for a in ax))
    return fn(x, axis=ax, keepdims=keepdims)


for _name, _f in {
    "sum": jnp.sum,
    "mean": jnp.mean,
    "prod": jnp.prod,
    "nansum": jnp.nansum,
    "nanprod": jnp.nanprod,
    "max": jnp.max,
    "min": jnp.min,
}.items():
    register(_name)(
        lambda x, axis=None, keepdims=False, exclude=False, _f=_f: _reduce(
            _f, x, axis, keepdims, exclude
        )
    )

register("sum_axis")(lambda x, axis=None, keepdims=False: _reduce(jnp.sum, x, axis, keepdims))
register("max_axis")(lambda x, axis=None, keepdims=False: _reduce(jnp.max, x, axis, keepdims))
register("min_axis")(lambda x, axis=None, keepdims=False: _reduce(jnp.min, x, axis, keepdims))


@register("norm")
def norm(x, ord=2, axis=None, keepdims=False):
    ax = _axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


@register("argmax", differentiable=False)
def argmax(x, axis=None, keepdims=False):
    ax = _axis(axis)
    out = jnp.argmax(x, axis=ax)
    if keepdims and ax is not None:
        out = jnp.expand_dims(out, ax)
    return out.astype(np.float32)  # MXNet returns float indices


@register("argmin", differentiable=False)
def argmin(x, axis=None, keepdims=False):
    ax = _axis(axis)
    out = jnp.argmin(x, axis=ax)
    if keepdims and ax is not None:
        out = jnp.expand_dims(out, ax)
    return out.astype(np.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(np.float32)


@register("topk", differentiable=False)
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import dtype_np

    if axis is None:
        # MXNet: axis=None selects the global top-k over the flattened array
        xm = x.reshape(-1)
        ax = 0
    else:
        ax = axis
        xm = jnp.moveaxis(x, ax, -1)
    if is_ascend:
        v, idx = jax.lax.top_k(-xm, k)
        v = -v
    else:
        v, idx = jax.lax.top_k(xm, k)
    v = jnp.moveaxis(v, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax).astype(dtype_np(dtype))
    if ret_typ == "value":
        return v
    if ret_typ == "both":
        return (v, idx)
    return idx


@register("sort", differentiable=False)
def sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import dtype_np

    idx = jnp.argsort(x, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(dtype_np(dtype))


@register("L2Normalization")
def l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "channel":
        denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    elif mode == "spatial":
        denom = jnp.sqrt(
            jnp.sum(jnp.square(x), axis=tuple(range(2, x.ndim)), keepdims=True) + eps
        )
    else:
        denom = jnp.sqrt(
            jnp.sum(jnp.square(x), axis=tuple(range(1, x.ndim)), keepdims=True) + eps
        )
    return x / denom
