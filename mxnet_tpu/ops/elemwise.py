"""Elementwise unary/binary/scalar ops.

Reference parity: src/operator/tensor/elemwise_unary_op_basic.*,
elemwise_binary_op_basic.*, elemwise_binary_broadcast_op_*,
src/operator/mshadow_op.h (~200 scalar functors).  On TPU these all lower to
single fused XLA HLO ops on the VPU; no hand-written kernels are needed.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register

# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------
_UNARY = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "softsign": lambda x: x / (1 + jnp.abs(x)),
    "tanh": jnp.tanh,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "square": jnp.square,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "rint": jnp.rint,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "negative": jnp.negative,
    "reciprocal": lambda x: 1.0 / x,
    "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv,
    "gamma": lambda x: jnp.exp(jax.lax.lgamma(x)),
    "gammaln": jax.lax.lgamma,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}

for _name, _f in _UNARY.items():
    register(_name)(lambda x, _f=_f: _f(x))

register("identity")(lambda x: x)
register("_copy")(lambda x: x)
register("stop_gradient", differentiable=False)(lambda x: jax.lax.stop_gradient(x))
register("BlockGrad", differentiable=False)(lambda x: jax.lax.stop_gradient(x))
register("make_loss")(lambda x: x)


@register("clip")
def clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register("add_n")
def add_n(*args, num_args=None):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("ElementWiseSum")
def element_wise_sum(*args, num_args=None):
    return add_n(*args)


@register("Cast", differentiable=False)
def cast(x, dtype="float32"):
    from ..base import dtype_np

    return x.astype(dtype_np(dtype))


# ---------------------------------------------------------------------------
# binary (broadcasting; MXNet's elemwise_* are the same math with shapes equal)
# ---------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
}
for _name, _f in _BINARY.items():
    register("broadcast_" + _name)(lambda a, b, _f=_f: _f(a, b))

register("elemwise_add")(lambda a, b: jnp.add(a, b))
register("elemwise_sub")(lambda a, b: jnp.subtract(a, b))
register("elemwise_mul")(lambda a, b: jnp.multiply(a, b))
register("elemwise_div")(lambda a, b: jnp.divide(a, b))
register("broadcast_plus")(lambda a, b: jnp.add(a, b))
register("broadcast_minus")(lambda a, b: jnp.subtract(a, b))

_CMP = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}
for _name, _f in _CMP.items():
    # MXNet comparison ops return the input dtype (1.0/0.0), not bool.
    register("broadcast_" + _name, differentiable=False)(
        lambda a, b, _f=_f: _f(a, b).astype(a.dtype)
    )


# ---------------------------------------------------------------------------
# scalar ops (back the NDArray operator sugar; reference:
# src/operator/tensor/elemwise_binary_scalar_op_*)
# ---------------------------------------------------------------------------
@register("_plus_scalar")
def _plus_scalar(x, scalar=0.0):
    return x + scalar


@register("_minus_scalar")
def _minus_scalar(x, scalar=0.0):
    return x - scalar


@register("_rminus_scalar")
def _rminus_scalar(x, scalar=0.0):
    return scalar - x


@register("_mul_scalar")
def _mul_scalar(x, scalar=1.0):
    return x * scalar


@register("_div_scalar")
def _div_scalar(x, scalar=1.0):
    return x / scalar


@register("_rdiv_scalar")
def _rdiv_scalar(x, scalar=1.0):
    return scalar / x


@register("_mod_scalar")
def _mod_scalar(x, scalar=1.0):
    return jnp.mod(x, scalar)


@register("_rmod_scalar")
def _rmod_scalar(x, scalar=1.0):
    return jnp.mod(scalar, x)


@register("_power_scalar")
def _power_scalar(x, scalar=1.0):
    return jnp.power(x, scalar)


@register("_rpower_scalar")
def _rpower_scalar(x, scalar=1.0):
    return jnp.power(scalar, x)


@register("_maximum_scalar")
def _maximum_scalar(x, scalar=0.0):
    return jnp.maximum(x, scalar)


@register("_minimum_scalar")
def _minimum_scalar(x, scalar=0.0):
    return jnp.minimum(x, scalar)


for _name, _f in _CMP.items():
    register(f"_{_name}_scalar", differentiable=False)(
        lambda x, scalar=0.0, _f=_f: _f(x, scalar).astype(x.dtype)
    )


@register("smooth_l1")
def smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(
        jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x), jnp.abs(x) - 0.5 / s2
    )


@register("digamma")
def digamma(x):
    return jax.scipy.special.digamma(x)


@register("all_finite", differentiable=False)
def all_finite(data, init_output=True):
    """1.0 when every element is finite, else 0.0 (reference
    src/operator/contrib/all_finite.cc) — the AMP overflow check.

    The reference's init_output=False AND-accumulates into the existing
    output buffer (chunked checks); this op layer is functional, so that
    mode is rejected rather than silently overwriting — pass all chunks
    to multi_all_finite instead.
    """
    if not init_output:
        from ..base import MXNetError

        raise MXNetError("all_finite: init_output=False (accumulate into "
                         "out) is not supported; check all arrays in one "
                         "multi_all_finite call instead")
    return jnp.isfinite(data.astype(jnp.float32)).all().astype(
        jnp.float32).reshape(1)


@register("multi_all_finite", differentiable=False)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    if not init_output:
        from ..base import MXNetError

        raise MXNetError("multi_all_finite: init_output=False is not "
                         "supported; pass all arrays in one call")
    ok = jnp.array(True)
    for a in arrays:
        ok = ok & jnp.isfinite(a.astype(jnp.float32)).all()
    return ok.astype(jnp.float32).reshape(1)


@register("maximum")
def maximum(lhs, rhs):
    """Elementwise max (reference: mx.nd.maximum, broadcasting)."""
    return jnp.maximum(lhs, rhs)


@register("minimum")
def minimum(lhs, rhs):
    """Elementwise min (reference: mx.nd.minimum, broadcasting)."""
    return jnp.minimum(lhs, rhs)
