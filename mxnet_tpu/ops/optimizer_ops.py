"""Fused optimizer update ops.

Reference parity: src/operator/optimizer_op.{cc,cu,-inl.h} — sgd_update,
sgd_mom_update, mp_sgd_* (fp16 weights + fp32 master copy), adam_update,
lamb_update_phase1/2, ftrl_update, signsgd/signum, multi-tensor variants.
Each is a single jitted XLA computation; XLA fuses the whole update chain
into one pass over the parameter, same as the reference's fused kernels.
All ops are non-differentiable state transitions.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight.astype(jnp.float32)


@register("sgd_update", differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@register("sgd_mom_update", differentiable=False)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_mom = momentum * mom.astype(jnp.float32) - lr * g
    new_w = weight.astype(jnp.float32) + new_mom
    return new_w.astype(weight.dtype), new_mom.astype(mom.dtype)


@register("mp_sgd_update", differentiable=False)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    g = _apply_wd_rescale(grad, weight32, rescale_grad, wd, clip_gradient)
    new32 = weight32 - lr * g
    return new32.astype(weight.dtype), new32


@register("mp_sgd_mom_update", differentiable=False)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _apply_wd_rescale(grad, weight32, rescale_grad, wd, clip_gradient)
    new_mom = momentum * mom - lr * g
    new32 = weight32 + new_mom
    return new32.astype(weight.dtype), new_mom, new32


@register("nag_mom_update", differentiable=False)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_mom = momentum * mom.astype(jnp.float32) + g
    new_w = weight.astype(jnp.float32) - lr * (g + momentum * new_mom)
    return new_w.astype(weight.dtype), new_mom.astype(mom.dtype)


@register("adam_update", differentiable=False)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w.astype(weight.dtype), new_mean, new_var


@register("mp_adam_update", differentiable=False)
def mp_adam_update(weight, grad, mean, var, weight32, lr=0.001, beta1=0.9,
                   beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _apply_wd_rescale(grad, weight32, rescale_grad, wd, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new32 = weight32 - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new32.astype(weight.dtype), new_mean, new_var, new32


@register("ftrl_update", differentiable=False)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight.astype(jnp.float32)
    denom = (beta + jnp.sqrt(new_n)) / lr + wd
    new_w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1) / denom,
        0.0,
    )
    return new_w.astype(weight.dtype), new_z, new_n


@register("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, 0.0, clip_gradient)
    new_w = weight.astype(jnp.float32) * (1 - lr * wd) - lr * jnp.sign(g)
    return new_w.astype(weight.dtype)


@register("signum_update", differentiable=False)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = weight.astype(jnp.float32) * (1 - lr * wd_lh) + lr * jnp.sign(new_mom)
    return new_w.astype(weight.dtype), new_mom


@register("rmsprop_update", differentiable=False)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n


@register("rmspropalex_update", differentiable=False)
def rmspropalex_update(weight, grad, n, g_buf, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_buf + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight.astype(jnp.float32) + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n, new_g, new_delta


@register("adagrad_update", differentiable=False)
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_hist = history + jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(new_hist) + epsilon)
    return new_w.astype(weight.dtype), new_hist


@register("adadelta_update", differentiable=False)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_rescale(grad, weight, rescale_grad, wd, clip_gradient)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    new_w = weight.astype(jnp.float32) - delta
    return new_w.astype(weight.dtype), new_acc_g, new_acc_delta


@register("lamb_update_phase1", differentiable=False)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1**t)
        v = v / (1 - beta2**t)
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight.astype(jnp.float32)
    return update, new_mean, new_var


@register("lamb_update_phase2", differentiable=False)
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.01, lower_bound=-1.0,
                       upper_bound=-1.0):
    r1v = r1.reshape(())
    r2v = r2.reshape(())
    if lower_bound >= 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound >= 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1v > 0, r2v > 0), r1v / r2v, 1.0)
    new_w = weight.astype(jnp.float32) - lr * ratio * g_update
    return new_w.astype(weight.dtype)


# ---------------------------------------------------------------------------
# multi-tensor apply variants (reference optimizer_op-inl.h ~L1500
# MultiSGDUpdate/MultiSGDMomUpdate + preloaded_* forms).  One op call
# updates many parameters; under jit XLA fuses the whole sweep.
# ---------------------------------------------------------------------------
def _norm_list(v, n):
    vals = [float(x) for x in (v if isinstance(v, (tuple, list)) else [v])]
    if len(vals) == 1:
        vals = vals * n
    return vals


@register("multi_sgd_update", differentiable=False)
def multi_sgd_update(*data, lrs=(0.01,), wds=(0.0,), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1):
    """data = [w0, g0, w1, g1, ...]; returns the updated weights."""
    n = int(num_weights)
    lrs = _norm_list(lrs, n)
    wds = _norm_list(wds, n)
    outs = []
    for i in range(n):
        w, g = data[2 * i], data[2 * i + 1]
        outs.append(sgd_update(w, g, lr=lrs[i], wd=wds[i],
                               rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient))
    return tuple(outs) if n > 1 else outs[0]


@register("multi_sgd_mom_update", differentiable=False)
def multi_sgd_mom_update(*data, lrs=(0.01,), wds=(0.0,), momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1):
    """data = [w0, g0, m0, w1, g1, m1, ...]; returns (w_i, m_i) pairs."""
    n = int(num_weights)
    lrs = _norm_list(lrs, n)
    wds = _norm_list(wds, n)
    outs = []
    for i in range(n):
        w, g, m = data[3 * i], data[3 * i + 1], data[3 * i + 2]
        nw, nm = sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                wd=wds[i], rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient)
        outs.extend([nw, nm])
    return tuple(outs)


@register("multi_mp_sgd_update", differentiable=False)
def multi_mp_sgd_update(*data, lrs=(0.01,), wds=(0.0,), rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1):
    """data = [w0, g0, w32_0, ...]; returns (w_i, w32_i) pairs."""
    n = int(num_weights)
    lrs = _norm_list(lrs, n)
    wds = _norm_list(wds, n)
    outs = []
    for i in range(n):
        w, g, w32 = data[3 * i], data[3 * i + 1], data[3 * i + 2]
        nw, n32 = mp_sgd_update(w, g, w32, lr=lrs[i], wd=wds[i],
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient)
        outs.extend([nw, n32])
    return tuple(outs)


@register("multi_mp_sgd_mom_update", differentiable=False)
def multi_mp_sgd_mom_update(*data, lrs=(0.01,), wds=(0.0,), momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1):
    """data = [w0, g0, m0, w32_0, ...]; returns (w_i, m_i, w32_i) triples."""
    n = int(num_weights)
    lrs = _norm_list(lrs, n)
    wds = _norm_list(wds, n)
    outs = []
    for i in range(n):
        w, g, m, w32 = (data[4 * i], data[4 * i + 1], data[4 * i + 2],
                        data[4 * i + 3])
        nw, nm, n32 = mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        outs.extend([nw, nm, n32])
    return tuple(outs)


@register("preloaded_multi_sgd_update", differentiable=False)
def preloaded_multi_sgd_update(*data, rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=1):
    """Like multi_sgd_update but lrs/wds arrive as trailing ARRAYS
    (reference preloaded_multi_sgd_update: scheduler-computed on device)."""
    n = int(num_weights)
    lrs, wds = data[-2], data[-1]
    outs = []
    for i in range(n):
        w, g = data[2 * i], data[2 * i + 1]
        g2 = _apply_wd_rescale(g, w, rescale_grad, wds[i], clip_gradient)
        outs.append((w.astype(jnp.float32) - lrs[i] * g2).astype(w.dtype))
    return tuple(outs) if n > 1 else outs[0]


@register("preloaded_multi_sgd_mom_update", differentiable=False)
def preloaded_multi_sgd_mom_update(*data, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=1):
    n = int(num_weights)
    lrs, wds = data[-2], data[-1]
    outs = []
    for i in range(n):
        w, g, m = data[3 * i], data[3 * i + 1], data[3 * i + 2]
        g2 = _apply_wd_rescale(g, w, rescale_grad, wds[i], clip_gradient)
        nm = momentum * m.astype(jnp.float32) - lrs[i] * g2
        outs.extend([(w.astype(jnp.float32) + nm).astype(w.dtype),
                     nm.astype(m.dtype)])
    return tuple(outs)
