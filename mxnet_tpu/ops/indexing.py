"""Indexing ops (reference: src/operator/tensor/indexing_op.* — take,
gather_nd, scatter_nd, one_hot, Embedding fwd/bwd)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype_np
from .registry import register


@register("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("pick")
def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    """Reference PickOpShape (src/operator/tensor/broadcast_reduce_op.h):
    the index may have the axis dim REMOVED or kept as size 1 — gluon's
    SoftmaxCrossEntropyLoss feeds (B,1) labels from ImageRecordIter and
    (B,) labels from NDArrayIter through the same op."""
    ax = axis % x.ndim
    idx = index.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, x.shape[ax])
    else:
        idx = jnp.clip(idx, 0, x.shape[ax] - 1)
    if idx.ndim == x.ndim - 1:
        idx = jnp.expand_dims(idx, ax)
    picked = jnp.take_along_axis(x, idx, axis=ax)
    return picked if keepdims else jnp.squeeze(picked, axis=ax)


@register("gather_nd")
def gather_nd(a, indices):
    idx = tuple(indices.astype(jnp.int32))
    return a[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=()):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(shape, data.dtype)
    return out.at[idx].set(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, indices, rhs, shape=()):
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


@register("one_hot", differentiable=False)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    return jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype_np(dtype)) * (
        on_value - off_value
    ) + off_value


@register("Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """Embedding lookup; gradient flows to `weight` via the vjp of take —
    XLA emits a scatter-add, the dense analog of the reference's
    row_sparse embedding backward (indexing_op.h EmbeddingOpBackward)."""
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                  axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    # broadcast steps along `axis` against batch on the other time/batch axis
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :].astype(steps.dtype)
        shape = mask.shape + (1,) * (data.ndim - 2)
        mask = mask.reshape(shape)
    else:
        mask = steps[None, :] < sequence_length[:, None].astype(steps.dtype)
        shape = mask.shape + (1,) * (data.ndim - 2)
        mask = mask.reshape(shape)
    return jnp.where(mask, data, value)


@register("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    dm = jnp.moveaxis(data, axis, 0)
    return jax.vmap(lambda t, i: t[i], in_axes=(1, 0))(dm, last)


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    dm = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    T = dm.shape[0]
    steps = jnp.arange(T)

    def rev_one(col, length):
        idx = jnp.where(steps < length, length - 1 - steps, steps)
        return col[idx]

    out = jax.vmap(rev_one, in_axes=(1, 0), out_axes=1)(dm, sequence_length.astype(jnp.int32))
    return jnp.moveaxis(out, 0, axis)


@register("boolean_mask", differentiable=False)
def boolean_mask(data, index, axis=0):
    # Dynamic-shape op: XLA needs static shapes, so we compact valid rows to
    # the front and return a full-size array (documented divergence; the
    # masked count is data.shape[axis] with invalid rows zeroed).
    mask = index != 0
    order = jnp.argsort(~mask, stable=True)
    gathered = jnp.take(data, order, axis=axis)
    keep = jnp.sort(mask)[::-1]
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return gathered * keep.reshape(shape).astype(data.dtype)


@register("_ravel_multi_index", differentiable=False)
def _ravel_multi_index(indices, shape=()):
    idx = indices.astype(jnp.int64)
    strides = np.concatenate([np.cumprod(np.asarray(shape)[::-1])[::-1][1:], [1]])
    return jnp.sum(idx * strides[:, None], axis=0).astype(jnp.int64)


@register("_unravel_index", differentiable=False)
def _unravel_index(indices, shape=()):
    out = jnp.stack(jnp.unravel_index(indices.astype(jnp.int64), shape))
    return out.astype(jnp.int64)


# public aliases (reference python/mxnet/ndarray/ndarray.py exposes these
# without the leading underscore)
@register("ravel_multi_index", differentiable=False)
def ravel_multi_index(indices, shape=()):
    return _ravel_multi_index(indices, shape=shape)


@register("unravel_index", differentiable=False)
def unravel_index(indices, shape=()):
    return _unravel_index(indices, shape=shape)
