"""Shape-manipulation and linear-algebra ops.

Reference parity: src/operator/tensor/matrix_op.* (transpose/reshape/slice/
concat/tile/... ~L1-3000), dot.{cc,cu} (GEMM dispatch to cuBLAS/rocBLAS).
On TPU `dot`/`batch_dot` lower straight onto the MXU via lax.dot_general.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import register


@register("reshape")
def reshape(x, shape=None, reverse=False):
    """MXNet reshape with special codes 0 (keep), -1 (infer), -2 (copy rest),
    -3 (merge two), -4 (split) — reference matrix_op-inl.h InferReshapeShape."""
    if shape is None:
        return x
    src = list(x.shape)
    if reverse:
        src = src[::-1]
        shape = list(shape)[::-1]
    out = []
    i = 0  # index into src
    j = 0
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s)
            if i < len(src):
                i += 1
        j += 1
    if reverse:
        out = out[::-1]
    if out.count(-1) == 1:
        known = int(np.prod([d for d in out if d != -1])) or 1
        out[out.index(-1)] = int(np.prod(x.shape)) // known
    return jnp.reshape(x, tuple(out))


@register("Reshape")
def Reshape(x, shape=None, reverse=False):
    return reshape(x, shape=shape, reverse=reverse)


@register("Flatten")
def flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose")
def transpose(x, axes=None):
    if axes is None or axes == ():
        axes = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, axes)


@register("expand_dims")
def expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze")
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis if axis is None else tuple(np.atleast_1d(axis)))


@register("swapaxes")
def swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("SwapAxis")
def SwapAxis(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("flip")
def flip(x, axis=0):
    return jnp.flip(x, axis)


@register("reverse")
def reverse(x, axis=0):
    return jnp.flip(x, axis)


@register("tile")
def tile(x, reps=()):
    return jnp.tile(x, reps)


@register("repeat")
def repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("broadcast_to")
def broadcast_to(x, shape=()):
    tgt = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_like")
def broadcast_like(x, y, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(x, y.shape)
    tgt = list(x.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la] = y.shape[ra]
    return jnp.broadcast_to(x, tuple(tgt))


@register("broadcast_axis")
def broadcast_axis(x, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("Concat")
def concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register("stack")
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


@register("split")
def split(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("SliceChannel")
def SliceChannel(x, num_outputs=1, axis=1, squeeze_axis=False):
    return split(x, num_outputs=num_outputs, axis=axis, squeeze_axis=squeeze_axis)


@register("slice")
def slice_op(x, begin=(), end=(), step=()):
    idx = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return x[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register("slice_axis")
def slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like")
def slice_like(x, shape_like, axes=()):
    axes = axes or tuple(range(min(x.ndim, shape_like.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return x[tuple(idx)]


@register("pad")
def pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(x, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise MXNetError(f"pad mode {mode}")


@register("dot")
def dot(a, b, transpose_a=False, transpose_b=False):
    """MXNet dot: contracts last axis of a with first axis of b (reference
    src/operator/tensor/dot-inl.h); rides the MXU via dot_general."""
    if transpose_a:
        a = jnp.transpose(a, tuple(range(1, a.ndim)) + (0,)) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.transpose(b, (b.ndim - 1,) + tuple(range(0, b.ndim - 1))) if b.ndim > 1 else b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("linalg_gemm2")
def linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0, axis=-3):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_syrk")
def linalg_syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("linalg_potrf")
def linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register("linalg_trsm")
def linalg_trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
        lower = not lower
    out = jax.scipy.linalg.solve_triangular(
        a, b if not rightside else jnp.swapaxes(b, -1, -2), lower=lower
    )
    if rightside:
        out = jnp.swapaxes(out, -1, -2)
    return alpha * out


@register("where")
def where(cond, a, b):
    return jnp.where(cond != 0, a, b)


@register("depth_to_space")
def depth_to_space(x, block_size=1):
    n, c, h, w = x.shape
    bs = block_size
    y = x.reshape(n, bs, bs, c // (bs * bs), h, w)
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    return y.reshape(n, c // (bs * bs), h * bs, w * bs)


@register("space_to_depth")
def space_to_depth(x, block_size=1):
    n, c, h, w = x.shape
    bs = block_size
    y = x.reshape(n, c, h // bs, bs, w // bs, bs)
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return y.reshape(n, c * bs * bs, h // bs, w // bs)


@register("diag")
def diag(x, k=0):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("shape_array", differentiable=False)
def shape_array(x):
    return jnp.asarray(x.shape, dtype=np.int64)


@register("size_array", differentiable=False)
def size_array(x):
    return jnp.asarray([x.size], dtype=np.int64)


@register("zeros_like_legacy", differentiable=False)
def zeros_like_legacy(x):
    return jnp.zeros_like(x)


# ---------------------------------------------------------------------------
# op tail (r3): batch_take, khatri_rao, linalg extras
# (reference: src/operator/tensor/indexing_op.cc batch_take, khatri_rao.cc,
# la_op.cc sumlogdiag/extractdiag/makediag/gelqf/inverse/det)
# ---------------------------------------------------------------------------
@register("batch_take")
def batch_take(a, indices):
    """out[i] = a[i, indices[i]] (reference: batch_take)."""
    idx = indices.astype(jnp.int32).reshape(-1)
    rows = jnp.arange(a.shape[0])
    return a[rows, jnp.clip(idx, 0, a.shape[1] - 1)]


@register("khatri_rao")
def khatri_rao(*matrices):
    """Column-wise Kronecker product (reference: khatri_rao.cc)."""
    out = matrices[0]
    for m in matrices[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[1])
    return out


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(a):
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(a, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag")
def linalg_makediag(a, offset=0):
    """np.diag semantics: (..., n) values -> (..., n+|k|, n+|k|) matrix
    with the values on diagonal k."""
    n = a.shape[-1]
    m = n + abs(int(offset))
    rows = np.arange(n) + max(-int(offset), 0)
    cols = np.arange(n) + max(int(offset), 0)
    out = jnp.zeros(a.shape[:-1] + (m, m), a.dtype)
    return out.at[..., rows, cols].set(a)


@register("linalg_inverse")
def linalg_inverse(a):
    return jnp.linalg.inv(a)


@register("linalg_det")
def linalg_det(a):
    return jnp.linalg.det(a)


@register("linalg_slogdet")
def linalg_slogdet(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


@register("linalg_gelqf", differentiable=False)
def linalg_gelqf(a):
    """LQ factorization A = L Q with Q orthonormal rows, returned as
    (Q, L) matching the reference calling convention `Q, L = gelqf(A)`
    (reference: la_op gelqf via LAPACK)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


@register("split_v2")
def split_v2(x, indices_or_sections=1, axis=0, squeeze_axis=False,
             sections=0):
    """numpy-style split (reference src/operator/tensor/matrix_op.cc
    _split_v2): int -> equal sections, tuple -> split points."""
    if sections and sections > 0:
        spec = int(sections)
    elif isinstance(indices_or_sections, int):
        spec = int(indices_or_sections)
    else:
        spec = [int(i) for i in indices_or_sections]
    outs = jnp.split(x, spec, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs) if len(outs) > 1 else outs[0]


@register("reshape_like")
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape (optionally only a dim range each side;
    reference matrix_op.cc reshape_like)."""
    lshape = list(lhs.shape)
    rshape = list(rhs.shape)

    def _resolve(idx, ndim, default):
        if idx is None:
            return default
        idx = int(idx)
        return idx + ndim if idx < 0 else idx  # MXNet negative-index rule

    lb = _resolve(lhs_begin, len(lshape), 0)
    le = _resolve(lhs_end, len(lshape), len(lshape))
    rb = _resolve(rhs_begin, len(rshape), 0)
    re_ = _resolve(rhs_end, len(rshape), len(rshape))
    new_shape = lshape[:lb] + rshape[rb:re_] + lshape[le:]
    return jnp.reshape(lhs, tuple(int(s) for s in new_shape))


@register("cumsum")
def cumsum(a, axis=None, dtype=None):
    out = jnp.cumsum(a if axis is not None else a.reshape(-1),
                     axis=axis if axis is not None else 0)
    if dtype is not None:
        from ..base import dtype_np

        out = out.astype(dtype_np(dtype))
    return out


@register("logsumexp")
def logsumexp(data, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(
        data, axis=axis if axis is None else tuple(
            [axis] if isinstance(axis, int) else axis), keepdims=keepdims)


@register("onehot_encode", differentiable=False)
def onehot_encode(indices, out_like):
    """Legacy onehot: indices (B,), out shape (B, C) taken from the second
    input (reference ndarray_function.cc OnehotEncode)."""
    c = out_like.shape[1]
    return jax.nn.one_hot(indices.astype(jnp.int32), c,
                          dtype=out_like.dtype)


@register("choose_element_0index", differentiable=False)
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] (legacy; reference ndarray_function.cc)."""
    idx = rhs.astype(jnp.int32)
    return jnp.take_along_axis(lhs, idx[:, None], axis=1)[:, 0]


@register("fill_element_0index", differentiable=False)
def fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i] (legacy)."""
    idx = rhs.astype(jnp.int32)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)


@register("linalg_gemm")
def linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    """C = alpha * op(A) op(B) + beta * C (reference la_op.cc gemm).
    `axis` is the position of the matrix-row dimension (the matrix spans
    (axis, axis+1); batch dims elsewhere)."""
    axis = int(axis)
    moved = axis not in (-2, a.ndim - 2)
    if moved:
        a = jnp.moveaxis(a, (axis, axis + 1), (-2, -1))
        b = jnp.moveaxis(b, (axis, axis + 1), (-2, -1))
        c = jnp.moveaxis(c, (axis, axis + 1), (-2, -1))
    ta = jnp.swapaxes(a, -1, -2) if transpose_a else a
    tb = jnp.swapaxes(b, -1, -2) if transpose_b else b
    out = alpha * jnp.matmul(ta, tb) + beta * c
    if moved:
        out = jnp.moveaxis(out, (-2, -1), (axis, axis + 1))
    return out


@register("linalg_trmm")
def linalg_trmm(a, b, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix product (reference la_op.cc trmm)."""
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b)
    return alpha * out


@register("linalg_potri")
def linalg_potri(a, lower=True):
    """Inverse from a Cholesky factor: (A A^T)^-1 given A
    (reference la_op.cc potri)."""
    n = a.shape[-1]
    eye = jnp.eye(n, dtype=a.dtype)
    inv_a = jax.scipy.linalg.solve_triangular(a, eye, lower=lower)
    return (jnp.swapaxes(inv_a, -1, -2) @ inv_a if lower
            else inv_a @ jnp.swapaxes(inv_a, -1, -2))
