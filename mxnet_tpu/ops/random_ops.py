"""Random sampling ops.

Reference parity: src/operator/random/sample_op.* over per-device Philox
streams (include/mxnet/random_generator.h ~L100).  TPU-native: jax's
counter-based threefry/rbg keys — the stateful MXNet seed facade lives in
mxnet_tpu.random, which threads an explicit key into every op here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import dtype_np
from .registry import register


@register("_random_uniform", differentiable=False)
def _random_uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(
        key, shape, dtype_np(dtype), minval=low, maxval=high
    )


@register("_random_normal", differentiable=False)
def _random_normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(key, shape, dtype_np(dtype))


@register("_random_gamma", differentiable=False)
def _random_gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(key, alpha, shape, dtype_np(dtype))


@register("_random_exponential", differentiable=False)
def _random_exponential(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(key, shape, dtype_np(dtype)) / lam


@register("_random_poisson", differentiable=False)
def _random_poisson(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(key, lam, shape).astype(dtype_np(dtype))


@register("_random_randint", differentiable=False)
def _random_randint(key, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(key, shape, low, high, dtype_np(dtype))


@register("_random_negative_binomial", differentiable=False)
def _random_negative_binomial(key, k=1, p=1.0, shape=(), dtype="float32"):
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, shape) * (1 - p) / p
    return jax.random.poisson(kp, lam, shape).astype(dtype_np(dtype))


@register("_random_generalized_negative_binomial", differentiable=False)
def _random_generalized_negative_binomial(key, mu=1.0, alpha=1.0, shape=(),
                                          dtype="float32"):
    kg, kp = jax.random.split(key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(kg, r, shape) * (1 - p) / p
    return jax.random.poisson(kp, lam, shape).astype(dtype_np(dtype))


@register("_sample_multinomial", differentiable=False)
def _sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    n = int(shape[0]) if shape else 1
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
    else:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(n,) + data.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    out = out.astype(dtype_np(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            out.astype(jnp.int32)[..., None] if data.ndim > 1 else out.astype(jnp.int32),
            axis=-1,
        )
        return out, logp.reshape(out.shape)
    return out


@register("_shuffle", differentiable=False)
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("sample_uniform", differentiable=False)
def sample_uniform(key, low, high, shape=(), dtype="float32"):
    s = tuple(shape) if shape else ()
    out_shape = low.shape + s
    u = jax.random.uniform(key, out_shape, dtype_np(dtype))
    low_b = low.reshape(low.shape + (1,) * len(s))
    high_b = high.reshape(high.shape + (1,) * len(s))
    return low_b + u * (high_b - low_b)


@register("sample_normal", differentiable=False)
def sample_normal(key, mu, sigma, shape=(), dtype="float32"):
    s = tuple(shape) if shape else ()
    out_shape = mu.shape + s
    z = jax.random.normal(key, out_shape, dtype_np(dtype))
    mu_b = mu.reshape(mu.shape + (1,) * len(s))
    sigma_b = sigma.reshape(sigma.shape + (1,) * len(s))
    return mu_b + z * sigma_b
