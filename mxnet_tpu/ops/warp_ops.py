"""Spatial warping / sampling ops: GridGenerator, BilinearSampler,
SpatialTransformer, Correlation.

Reference parity: src/operator/{grid_generator,bilinear_sampler,
spatial_transformer,correlation}{.cc,.cu,-inl.h} (cuDNN spatial-tf path in
cudnn_spatial_transformer-inl.h).

TPU-native design: the gather-heavy bilinear sampling is expressed as
vectorized jnp.take along flattened spatial indices (XLA lowers this onto
the TPU gather unit); the FlowNet correlation is a static unrolled loop
over the (small) displacement grid of fused elementwise multiplies +
channel reductions — no im2col materialization, and every branch is
statically shaped so the MXU/VPU tiling is clean.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _bilinear_sample(data, x, y):
    """Sample data (B,C,H,W) at absolute pixel coords x,y (B,Ho,Wo) with
    zero padding outside the image (reference bilinear_sampler-inl.h
    between_pad semantics)."""
    B, C, H, W = data.shape
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = (x - x0)[:, None]  # (B,1,Ho,Wo)
    wy = (y - y0)[:, None]

    def gather(yi, xi):
        valid = ((xi >= 0) & (xi <= W - 1) & (yi >= 0)
                 & (yi <= H - 1))[:, None]
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        flat = data.reshape(B, C, H * W)
        idx = (yc * W + xc).reshape(B, 1, -1)
        vals = jnp.take_along_axis(flat, jnp.broadcast_to(
            idx, (B, C, idx.shape[-1])), axis=2)
        vals = vals.reshape(B, C, *xi.shape[1:])
        return jnp.where(valid, vals, jnp.zeros((), data.dtype))

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx = wx.astype(data.dtype)
    wy = wy.astype(data.dtype)
    return ((1 - wy) * ((1 - wx) * v00 + wx * v01)
            + wy * ((1 - wx) * v10 + wx * v11))


@register("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=False):
    """data (B,C,H,W), grid (B,2,Ho,Wo) normalized to [-1,1]
    (grid[:,0]=x, grid[:,1]=y); zero padding outside."""
    _, _, H, W = data.shape
    gx = grid[:, 0].astype(jnp.float32)
    gy = grid[:, 1].astype(jnp.float32)
    x = (gx + 1) * (W - 1) / 2
    y = (gy + 1) * (H - 1) / 2
    return _bilinear_sample(data, x, y)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """affine: data (B,6) -> normalized sampling grid (B,2,H,W);
    warp: data = flow (B,2,H,W) in pixels -> normalized grid."""
    if transform_type == "affine":
        H, W = int(target_shape[0]), int(target_shape[1])
        B = data.shape[0]
        theta = data.reshape(B, 2, 3).astype(jnp.float32)
        ys, xs = jnp.meshgrid(jnp.linspace(-1, 1, H), jnp.linspace(-1, 1, W),
                              indexing="ij")
        ones = jnp.ones_like(xs)
        coords = jnp.stack([xs, ys, ones], 0).reshape(3, H * W)
        out = jnp.einsum("bij,jk->bik", theta, coords)  # (B,2,H*W)
        return out.reshape(B, 2, H, W).astype(data.dtype)
    # warp: pixel flow added to the identity pixel grid, renormalized
    B, _, H, W = data.shape
    flow = data.astype(jnp.float32)
    ys, xs = jnp.meshgrid(jnp.arange(H, dtype=jnp.float32),
                          jnp.arange(W, dtype=jnp.float32), indexing="ij")
    x = xs[None] + flow[:, 0]
    y = ys[None] + flow[:, 1]
    gx = 2 * x / jnp.maximum(W - 1, 1) - 1
    gy = 2 * y / jnp.maximum(H - 1, 1) - 1
    return jnp.stack([gx, gy], 1).astype(data.dtype)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=False):
    """Affine grid from loc (B,6) + bilinear sampling of data
    (reference spatial_transformer-inl.h)."""
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)


@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (reference src/operator/correlation-inl.h).

    Output (B, D*D, Ho, Wo) where D = 2*floor(max_displacement/stride2)+1:
    channel-mean of data1*shift(data2) (or |a-b| when is_multiply=False)
    averaged over the kernel_size window, displacement-major ordering.
    """
    B, C, H, W = data1.shape
    k = int(kernel_size)
    pad = int(pad_size)
    rad = k // 2
    d_unit = int(max_displacement) // int(stride2)
    D = 2 * d_unit + 1
    # padded canvases; data1 only needs the kernel radius, data2 the full pad
    p1 = jnp.pad(data1.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    # output spatial extent (reference: kernel_radius_+max_displacement border)
    border = rad + int(max_displacement)
    Hp, Wp = H + 2 * pad, W + 2 * pad
    Ho = (Hp - 2 * border + int(stride1) - 1) // int(stride1)
    Wo = (Wp - 2 * border + int(stride1) - 1) // int(stride1)
    ys = border + jnp.arange(Ho) * int(stride1)
    xs = border + jnp.arange(Wo) * int(stride1)

    def window_mean(prod_map, oy=0, ox=0):
        """k x k patch mean of a (B,Ho',Wo')-shaped map at the strided
        centers — applied AFTER the pixelwise product, matching the
        reference's sum over patch offsets of aligned products."""
        acc = 0.0
        for dy in range(-rad, rad + 1):
            for dx in range(-rad, rad + 1):
                rows = jnp.clip(ys + oy + dy, 0, Hp - 1)
                cols = jnp.clip(xs + ox + dx, 0, Wp - 1)
                acc = acc + prod_map[:, rows][:, :, cols]
        return acc / (k * k)

    outs = []
    for dy in range(-d_unit, d_unit + 1):
        for dx in range(-d_unit, d_unit + 1):
            oy, ox = dy * int(stride2), dx * int(stride2)
            # align data2 with data1 at this displacement, then reduce
            shifted = jnp.roll(p2, (-oy, -ox), axis=(2, 3))
            if is_multiply:
                pm = jnp.mean(p1 * shifted, axis=1)  # (B,Hp,Wp)
            else:
                pm = jnp.mean(jnp.abs(p1 - shifted), axis=1)
            # zero out wrapped-around rows/cols from the roll
            row_ok = jnp.arange(Hp) + oy
            col_ok = jnp.arange(Wp) + ox
            valid = ((row_ok >= 0) & (row_ok < Hp))[:, None] & \
                    ((col_ok >= 0) & (col_ok < Wp))[None, :]
            pm = jnp.where(valid[None], pm, 0.0)
            outs.append(window_mean(pm))
    out = jnp.stack(outs, axis=1)  # (B, D*D, Ho, Wo)
    return out.astype(data1.dtype)
