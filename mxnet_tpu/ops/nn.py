"""Neural-network layer ops.

Reference parity: src/operator/nn/ (convolution, fully_connected, batch_norm,
pooling, activation, softmax, dropout, layer_norm, lrn, upsampling ...) and
the cuDNN/MIOpen wrapper family.  On TPU the vendor-library role is played by
XLA itself: conv/matmul lower onto the MXU (lax.conv_general_dilated /
dot_general), normalizations and activations fuse into neighbouring HLO.
Spatial ops default to MXNet's native NC[DHW] layouts; conv/pool also accept
the channel-last layouts (NWC/NHWC/NDHWC) the reference reserves for its
tensor-core paths — on TPU channel-last is the MXU-friendly tiling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, is_float_dtype
from .registry import register


def _pair(v, n):
    if v is None or v == ():
        return (0,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _safe_acc(data, weight):
    """fp16 safe accumulation: fp16 partial sums overflow at ~65504, so
    matmul/conv inputs are upcast to f32 (MXNET_SAFE_ACCUMULATION).  The
    upcast-inputs pattern (not preferred_element_type) keeps the transpose
    rules dtype-consistent under value_and_grad.  bf16 needs nothing: the
    MXU accumulates bf16 in f32 natively."""
    if np.dtype(data.dtype) == np.float16:
        return data.astype(jnp.float32), weight.astype(jnp.float32), True
    return data, weight, False


# ---------------------------------------------------------------------------
# dense / conv
# ---------------------------------------------------------------------------
@register("FullyConnected")
def fully_connected(data, weight, *bias, num_hidden=None, no_bias=False, flatten=True):
    """y = x W^T + b (reference: src/operator/nn/fully_connected-inl.h).

    Weight layout (num_hidden, input_dim), matching MXNet exactly.
    """
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    # No explicit preferred_element_type: an f32 output + astype breaks the
    # transpose rules under value_and_grad (the cotangent arrives f32
    # against bf16 saved operands — the BENCH_r02 failure mode).
    x, w, downcast = _safe_acc(x, weight)
    y = jax.lax.dot_general(
        x, w,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
    )
    if downcast:
        y = y.astype(data.dtype)
    if not no_bias and bias:
        y = y + bias[0]
    return y


def _conv_dims(kernel):
    return len(kernel)


def _channels_last(layout):
    """True for MXNet channel-last layouts (NWC/NHWC/NDHWC).

    The reference supports these for cuDNN tensor-core paths
    (src/operator/nn/convolution.cu layout-specialized kernels); on TPU the
    channel-last path is the MXU-friendly tiling — XLA avoids the implicit
    layout conversions it inserts around NCHW convs.
    """
    return layout is not None and layout.endswith("C") and layout != "NC"


@register("Convolution")
def convolution(data, weight, *bias, kernel=(), stride=(), dilate=(), pad=(),
                num_filter=1, num_group=1, no_bias=False, workspace=1024,
                cudnn_tune=None, cudnn_off=False, layout=None):
    """N-d convolution (reference src/operator/nn/convolution-inl.h).

    Weight layout follows the data layout as in MXNet: OI<spatial> for
    NC-first (default), O<spatial>I for channel-last (NHWC family).
    cudnn_* attrs are accepted and ignored: algorithm selection is XLA's job.
    """
    n = _conv_dims(kernel)
    stride = _pair(stride or 1, n)
    dilate = _pair(dilate or 1, n)
    pad = _pair(pad, n)
    spatial = "DHW"[-n:]
    if _channels_last(layout):
        specs = ("N" + spatial + "C", "O" + spatial + "I", "N" + spatial + "C")
    else:
        specs = ("NC" + spatial, "OI" + spatial, "NC" + spatial)
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, specs)
    lhs, rhs, downcast = _safe_acc(data, weight)
    out = jax.lax.conv_general_dilated(
        lhs, rhs,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if downcast:
        out = out.astype(data.dtype)
    if not no_bias and bias:
        if _channels_last(layout):
            b = bias[0].reshape((1,) * (n + 1) + (-1,))
        else:
            b = bias[0].reshape((1, -1) + (1,) * n)
        out = out + b
    return out


@register("Deconvolution")
def deconvolution(data, weight, *bias, kernel=(), stride=(), dilate=(), pad=(),
                  adj=(), target_shape=(), num_filter=1, num_group=1,
                  no_bias=True, workspace=1024, cudnn_tune=None, cudnn_off=False,
                  layout=None):
    """Transposed convolution (reference src/operator/nn/deconvolution-inl.h).
    Weight layout (C_in, C_out/group, *kernel) as in MXNet."""
    n = _conv_dims(kernel)
    if target_shape:
        # MXNet derives pad from target_shape; silently ignoring it would
        # return a differently-padded tensor
        raise NotImplementedError(
            "Deconvolution target_shape is not supported; give pad/adj "
            "explicitly (out = (in-1)*s - 2p + d*(k-1) + 1 + adj)")
    stride = _pair(stride or 1, n)
    dilate = _pair(dilate or 1, n)
    pad = _pair(pad, n)
    adj = _pair(adj, n) if adj else (0,) * n
    spatial = "DHW"[-n:]
    lhs, rhs, downcast = _safe_acc(data, weight)
    # transposed conv = dilated conv with the SPATIALLY FLIPPED kernel
    # (conv_general_dilated correlates; the gradient-of-conv semantics
    # need the flip) ...
    if _channels_last(layout):
        sp_axes = tuple(range(1, 1 + n))  # weight (I, *k, O)
        specs = ("N" + spatial + "C", "I" + spatial + "O", "N" + spatial + "C")
    else:
        sp_axes = tuple(range(2, 2 + n))  # weight (I, O/g, *k)
        specs = ("NC" + spatial, "IO" + spatial, "NC" + spatial)
    rhs = jnp.flip(rhs, sp_axes)
    if num_group > 1:
        # ... and grouped weights regroup to what feature_group_count
        # expects: rhs (I/g, O_total, *k) where O-blocks line up with the
        # input-channel blocks.  (C_in, C_out/g, *k) ->
        # (g, C_in/g, C_out/g, *k) -> (C_in/g, g, C_out/g, *k) ->
        # (C_in/g, C_out, *k)
        if _channels_last(layout):
            # (C_in, *k, C_out/g): move I to front grouping similarly
            cin = rhs.shape[0]
            rhs = rhs.reshape((num_group, cin // num_group)
                              + rhs.shape[1:])
            rhs = jnp.moveaxis(rhs, 0, -2)  # (C_in/g, *k, g, C_out/g)
            rhs = rhs.reshape(rhs.shape[:-2]
                              + (num_group * rhs.shape[-1],))
        else:
            cin = rhs.shape[0]
            rhs = rhs.reshape((num_group, cin // num_group)
                              + rhs.shape[1:])
            rhs = jnp.swapaxes(rhs, 0, 1)  # (C_in/g, g, C_out/g, *k)
            rhs = rhs.reshape((cin // num_group,
                               num_group * rhs.shape[2]) + rhs.shape[3:])
    dn = jax.lax.conv_dimension_numbers(data.shape, rhs.shape, specs)
    # lhs_dilation implements the fractional stride; padding chosen so that
    # out = (in-1)*s - 2p + dilate*(k-1) + 1 + adj  (MXNet's formula)
    pads = []
    for i in range(n):
        k = dilate[i] * (kernel[i] - 1) + 1
        lo = k - 1 - pad[i]
        hi = k - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    out = jax.lax.conv_general_dilated(
        lhs, rhs,
        window_strides=(1,) * n,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if downcast:
        out = out.astype(data.dtype)
    if not no_bias and bias:
        if _channels_last(layout):
            out = out + bias[0].reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias[0].reshape((1, -1) + (1,) * n)
    return out


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------
@register("Pooling")
def pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(),
            pad=(), pooling_convention="valid", count_include_pad=True,
            cudnn_off=False, p_value=2, layout=None):
    """Spatial pooling (reference src/operator/nn/pooling-inl.h).

    Channel-last layouts (NWC/NHWC/NDHWC) pool over the middle dims."""
    n = data.ndim - 2
    last = _channels_last(layout)
    sp0 = 1 if last else 2  # first spatial dim index
    if global_pool:
        kernel = data.shape[sp0:sp0 + n]
        stride = (1,) * n
        pad = (0,) * n
    kernel = _pair(kernel, n)
    stride = _pair(stride or 1, n)
    pad = _pair(pad, n)

    if last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad on the high side enough to cover the last window
        extra = []
        for i in range(n):
            in_i = data.shape[sp0 + i]
            out_i = int(np.ceil((in_i + 2 * pad[i] - kernel[i]) / stride[i])) + 1
            need = (out_i - 1) * stride[i] + kernel[i] - in_i - pad[i]
            extra.append(max(need, pad[i]))
        sp_pads = tuple((pad[i], extra[i]) for i in range(n))
    else:
        sp_pads = tuple((p, p) for p in pad)
    if last:
        pads = ((0, 0),) + sp_pads + ((0, 0),)
    else:
        pads = ((0, 0), (0, 0)) + sp_pads

    # dtype-safe identities: bfloat16 (ml_dtypes) reports numpy kind 'V',
    # so go through jnp.issubdtype rather than dtype.kind (the BENCH_r02
    # crash).  The identities must be HOST numpy scalars — lax only
    # recognizes the max/add monoid (and thus differentiates the window
    # reduce) for literal init values, not traced jnp constants.
    dt = np.dtype(data.dtype)
    if pool_type == "max":
        if is_float_dtype(dt):
            init = np.array(-np.inf, dt)
        else:
            init = np.array(np.iinfo(dt).min, dt)
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, pads)
    zero = np.zeros((), dt)
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(data, zero, jax.lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = float(np.prod(kernel))
            return summed / denom
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(ones, zero, jax.lax.add, window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        powed = jax.lax.reduce_window(
            jnp.abs(data) ** p_value, zero, jax.lax.add, window, strides, pads
        )
        return powed ** (1.0 / p_value)
    raise MXNetError(f"pool_type {pool_type}")


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------
@register("Activation")
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError(f"act_type {act_type}")


@register("LeakyReLU")
def leaky_relu(data, *gamma, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "prelu":
        g = gamma[0]
        shape = [1] * data.ndim
        if data.ndim > 1:
            shape[1] = g.size
        return jnp.where(data >= 0, data, g.reshape(shape) * data)
    if act_type == "rrelu":
        # deterministic mid-slope outside training (reference uses RNG in train)
        mid = (lower_bound + upper_bound) / 2
        return jnp.where(data >= 0, data, mid * data)
    raise MXNetError(f"act_type {act_type}")


# ---------------------------------------------------------------------------
# softmax family
# ---------------------------------------------------------------------------
@register("softmax")
def softmax(data, *length, axis=-1, temperature=None, dtype=None,
            use_length=False):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.softmax(-x, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=logp.dtype)
    return jnp.sum(-logp * onehot)


_softmax_output_cache = {}


def _make_softmax_output(grad_scale, ignore_label, use_ignore, multi_output,
                         normalization, smooth_alpha):
    """Build a custom_vjp softmax-output closed over its (static) attrs.

    Legacy semantics: backward IGNORES the incoming cotangent and emits
    (p - onehot(label)) scaled — reference src/operator/nn/softmax_output-inl.h.
    """
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def fwd(data, label):
        return jax.nn.softmax(data, axis=axis)

    def f(data, label):
        out = jax.nn.softmax(data, axis=axis)
        return out, (out, label)

    def b(res, g):
        out, label = res
        k = out.shape[axis]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), k, dtype=out.dtype)
        if multi_output:
            onehot = jnp.moveaxis(onehot, -1, 1)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (k - 1) * (1 - onehot)
        grad = out - onehot
        if use_ignore:
            mask = (label != ignore_label).astype(out.dtype)
            if mask.ndim < grad.ndim:
                mask = jnp.expand_dims(mask, axis)
            grad = grad * mask
        scale = grad_scale
        if normalization == "valid" and use_ignore:
            valid = jnp.maximum(jnp.sum(label != ignore_label).astype(out.dtype), 1.0)
            scale = grad_scale / valid
        elif normalization == "batch":
            scale = grad_scale / out.shape[0]
        if is_float_dtype(label.dtype):  # incl. bfloat16 (numpy kind 'V')
            lab_ct = jnp.zeros_like(label)
        else:  # integer labels: jax requires a float0 cotangent
            lab_ct = np.zeros(label.shape, dtype=jax.dtypes.float0)
        return (grad * scale, lab_ct)

    fwd.defvjp(f, b)
    return fwd


@register("SoftmaxOutput")
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    key = (grad_scale, ignore_label, use_ignore, multi_output, normalization,
           smooth_alpha)
    fn = _softmax_output_cache.get(key)
    if fn is None:
        fn = _make_softmax_output(*key)
        _softmax_output_cache[key] = fn
    return fn(data, label)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
@register("BatchNorm")
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, training=False):
    """BatchNorm (reference src/operator/nn/batch_norm-inl.h).

    Pure function: in training mode returns (out, batch_mean, batch_var) when
    output_mean_var so the caller (gluon.nn.BatchNorm) can update the moving
    aux states — the reference mutates aux in-op; we keep the op pure for XLA.
    `training` comes from autograd train-mode, threaded by the caller.
    """
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    axis = axis % data.ndim  # normalize negatives (axis=-1 for NHWC nets)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    red = tuple(i for i in range(data.ndim) if i != axis)
    if training and not use_global_stats:
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red)
        var = jnp.mean(jnp.square(x32 - mean.reshape(shape)), axis=red)
    else:
        mean, var = moving_mean, moving_var
    inv = jax.lax.rsqrt(var.reshape(shape) + eps).astype(data.dtype)
    out = (data - mean.reshape(shape).astype(data.dtype)) * inv * g.reshape(
        shape
    ).astype(data.dtype) + beta.reshape(shape).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    if (not output_mean_var and axis in (-1, data.ndim - 1)
            and data.ndim >= 2):
        from . import pallas as _pk

        if _pk.enabled() and _pk.use_compiled():
            out = _pk.layer_norm(data.reshape(-1, data.shape[-1]), gamma,
                                 beta, eps=eps)
            return out.reshape(data.shape)
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = ((x32 - mean) * inv).astype(data.dtype) * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@register("_contrib_add_layer_norm")
def add_layer_norm(data, residual, gamma, beta, eps=1e-5):
    """Residual add + last-axis layer norm: LN(data + residual).  The
    pre-norm transformer block boundary as ONE op-class, so the
    fused_kernels pass can substitute the single-VMEM-pass Pallas kernel
    (ops/pallas/fused.add_layer_norm); this stock implementation is the
    bitwise-parity path when the pass is off."""
    x32 = data.astype(jnp.float32) + residual.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    out_dtype = jnp.result_type(data.dtype, residual.dtype)
    shape = [1] * data.ndim
    shape[-1] = data.shape[-1]
    return ((x32 - mean) * inv).astype(out_dtype) * gamma.reshape(
        shape) + beta.reshape(shape)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(shape) + beta.reshape(shape)


@register("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    spatial = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + spatial)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=red, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    window = jnp.stack(
        [padded[:, i : i + data.shape[1]] for i in range(nsize)], axis=0
    ).sum(axis=0)
    return data / jnp.power(knorm + alpha / nsize * window, beta)


# ---------------------------------------------------------------------------
# dropout (RNG key threaded explicitly; see mxnet_tpu.random)
# ---------------------------------------------------------------------------
@register("Dropout", differentiable=True)
def dropout(data, key, p=0.5, mode="training", axes=(), training=False,
            cudnn_off=False):
    if not training or p <= 0.0:
        return data
    # `axes` = variational dropout: the mask is broadcast along those axes
    shape = [1 if i in axes else data.shape[i] for i in range(data.ndim)]
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# ---------------------------------------------------------------------------
# resize
# ---------------------------------------------------------------------------
@register("UpSampling")
def upsampling(*inputs, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    data = inputs[0]
    n, c, h, w = data.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    else:
        out = jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")
    return out


@register("BilinearResize2D")
def bilinear_resize_2d(data, height=1, width=1, scale_height=None, scale_width=None,
                       mode="size"):
    n, c, h, w = data.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    return jax.image.resize(data, (n, c, height, width), method="bilinear")


# ---------------------------------------------------------------------------
# regression output heads (reference: src/operator/regression_output-inl.h)
# Legacy semantics like SoftmaxOutput: backward IGNORES the incoming
# cotangent and emits the analytic per-element residual * grad_scale.
# ---------------------------------------------------------------------------
def _make_regression_output(transform, residual, grad_scale):
    @jax.custom_vjp
    def fwd(data, label):
        return transform(data)

    def f(data, label):
        out = transform(data)
        return out, (out, label)

    def b(res, g):
        out, label = res
        return (residual(out, label) * grad_scale, jnp.zeros_like(label))

    fwd.defvjp(f, b)
    return fwd


_regression_cache = {}


def _regression_output(kind, data, label, grad_scale):
    key = (kind, grad_scale)
    fn = _regression_cache.get(key)
    if fn is None:
        transform = {"linear": lambda x: x,
                     "mae": lambda x: x,
                     "logistic": jax.nn.sigmoid}[kind]
        residual = {"linear": lambda o, l: o - l,
                    "mae": lambda o, l: jnp.sign(o - l),
                    "logistic": lambda o, l: o - l}[kind]
        fn = _make_regression_output(transform, residual, grad_scale)
        _regression_cache[key] = fn
    return fn(data, label.reshape(data.shape))


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    return _regression_output("linear", data, label, grad_scale)


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    return _regression_output("mae", data, label, grad_scale)


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    return _regression_output("logistic", data, label, grad_scale)


@register("MakeLoss")
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Forward identity; backward seeds grad_scale as the gradient,
    normalized by batch size or by the count of elements above valid_thresh
    (reference: src/operator/make_loss-inl.h)."""
    @jax.custom_vjp
    def fwd(x):
        return x

    def f(x):
        return x, x

    def b(x, g):
        if normalization == "batch":
            denom = jnp.asarray(x.shape[0], x.dtype)
        elif normalization == "valid":
            denom = jnp.maximum(
                jnp.sum(x > valid_thresh).astype(x.dtype), 1.0)
        else:
            denom = jnp.asarray(1.0, x.dtype)
        return (jnp.full_like(g, grad_scale) / denom,)

    fwd.defvjp(f, b)
    return fwd(data)


# ---------------------------------------------------------------------------
# CTC loss (reference: src/operator/nn/ctc_loss.cc + gluon CTCLoss)
# ---------------------------------------------------------------------------
def _ctc_forward(logp, t_len, ext, s_valid, skip_ok):
    """Log-space CTC alpha recursion for ONE sequence.

    logp: (T, C) log-softmax scores; ext: (S,) extended label seq
    (blank-interleaved, S = 2*Lmax+1); s_valid: number of valid ext slots
    (2*label_len+1); skip_ok: (S,) whether the s-2 skip transition is legal.
    Returns the log-likelihood; differentiating this scan IS the standard
    CTC gradient.
    """
    NEG = -1e30
    S = ext.shape[0]
    alpha0 = jnp.full((S,), NEG)
    alpha0 = alpha0.at[0].set(logp[0, ext[0]])
    alpha0 = alpha0.at[1].set(jnp.where(s_valid > 1, logp[0, ext[1]], NEG))

    def step(alpha, lp_t):
        a1 = jnp.concatenate([jnp.full((1,), NEG), alpha[:-1]])
        a2 = jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]])
        a2 = jnp.where(skip_ok, a2, NEG)
        m = jnp.maximum(alpha, jnp.maximum(a1, a2))
        tot = m + jnp.log(jnp.exp(alpha - m) + jnp.exp(a1 - m)
                          + jnp.exp(a2 - m))
        new = tot + lp_t[ext]
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas])  # (T, S)
    final = alphas[t_len - 1]
    last = final[s_valid - 1]
    # empty label (s_valid == 1): only the all-blank path exists — do not
    # logsumexp final[0] with itself
    prev = jnp.where(s_valid > 1, final[jnp.maximum(s_valid - 2, 0)], NEG)
    m = jnp.maximum(last, prev)
    return m + jnp.log(jnp.exp(last - m) + jnp.exp(prev - m))


@register("ctc_loss")
def ctc_loss(data, label, *lengths, use_data_lengths=False,
             use_label_lengths=False, blank_label="first"):
    """Connectionist Temporal Classification loss.

    data: (T, N, C) activations (softmax applied internally, reference
    semantics); label: (N, Lmax) class ids, values < 0 are padding.
    Optional data_lengths/label_lengths NDArrays follow positionally when
    the corresponding use_* flag is set.  blank_label 'first' -> blank id
    0 (labels use 1..C-1); 'last' -> blank id C-1 (labels use 0..C-2).
    Returns per-example loss (N,).
    """
    T, N, C = data.shape
    blank = 0 if blank_label == "first" else C - 1
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)

    li = 0
    if use_data_lengths:
        t_lens = lengths[li].astype(jnp.int32)
        li += 1
    else:
        t_lens = jnp.full((N,), T, jnp.int32)
    lab = label.astype(jnp.int32)
    if use_label_lengths:
        l_lens = lengths[li].astype(jnp.int32)
    else:
        # padding convention (reference ctc_loss doc): blank_label='first'
        # reserves id 0 for blank AND uses 0 as label padding (real labels
        # are 1..C-1); 'last' uses -1 padding (labels 0..C-2)
        if blank_label == "first":
            l_lens = (lab > 0).sum(axis=1).astype(jnp.int32)
        else:
            l_lens = (lab >= 0).sum(axis=1).astype(jnp.int32)
    lab = jnp.maximum(lab, 0)

    Lmax = lab.shape[1]
    blanks = jnp.full((N, Lmax), blank, jnp.int32)
    ext = jnp.stack([blanks, lab], axis=2).reshape(N, 2 * Lmax)
    ext = jnp.concatenate([ext, blanks[:, :1]], axis=1)  # (N, 2Lmax+1)
    skip_ok = jnp.concatenate(
        [jnp.zeros((N, 2), bool),
         (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)
    s_valid = 2 * l_lens + 1
    ll = jax.vmap(_ctc_forward, in_axes=(1, 0, 0, 0, 0))(
        logp, t_lens, ext, s_valid, skip_ok)
    return (-ll).astype(data.dtype)


# ---------------------------------------------------------------------------
# legacy spatial utility ops (reference src/operator/pad.cc, crop.cc,
# nn/im2col.h, nn/moments.cc, svm_output.cc)
# ---------------------------------------------------------------------------
@register("Pad")
def pad_op(data, mode="constant", pad_width=(), constant_value=0.0):
    """Pad (reference src/operator/pad.cc): pad_width is the flat
    (before, after) pair per axis, mxnet convention."""
    pw = [(int(pad_width[2 * i]), int(pad_width[2 * i + 1]))
          for i in range(data.ndim)]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=jnp.asarray(
            constant_value, data.dtype))
    return jnp.pad(data, pw, mode={"edge": "edge", "reflect": "reflect"}[mode])


@register("Crop")
def crop_op(*inputs, offset=(0, 0), h_w=(0, 0), num_args=1,
            center_crop=False):
    """Crop data (B,C,H,W) to h_w, or to the spatial size of a second
    reference input (reference src/operator/crop.cc)."""
    data = inputs[0]
    if num_args == 2 and len(inputs) > 1:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    return data[:, :, y0:y0 + th, x0:x0 + tw]


@register("moments")
def moments(data, axes=None, keepdims=False):
    """Mean and variance over axes (reference src/operator/nn/moments.cc)."""
    ax = tuple(int(a) for a in axes) if axes is not None else None
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=ax, keepdims=keepdims)
    if not keepdims:
        mean = jnp.squeeze(mean, axis=ax)
    return mean.astype(data.dtype), var.astype(data.dtype)


_svm_output_cache = {}


def _make_svm_output(margin, reg_coef, use_linear):
    """Legacy output-op semantics like SoftmaxOutput: forward is identity,
    backward ignores the cotangent and emits the hinge-loss gradient
    (reference src/operator/svm_output-inl.h)."""

    @jax.custom_vjp
    def fwd(data, label):
        return data

    def f(data, label):
        return data, (data, label)

    def b(res, g):
        data, label = res
        x32 = data.astype(jnp.float32)
        k = data.shape[-1]
        lab = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, k, dtype=jnp.float32)
        scores_y = jnp.sum(x32 * onehot, axis=-1, keepdims=True)
        viol = margin - scores_y + x32  # (..., k); at y: margin exactly
        if use_linear:  # L1-SVM: +-reg on violating classes
            mask = ((viol > 0) & (onehot == 0)).astype(jnp.float32)
            grad = reg_coef * mask
        else:  # L2-SVM: gradient proportional to the violation
            mask = ((viol > 0) & (onehot == 0)).astype(jnp.float32)
            grad = 2.0 * reg_coef * viol * mask
        grad = grad - onehot * jnp.sum(grad, axis=-1, keepdims=True)
        if is_float_dtype(label.dtype):
            lab_ct = jnp.zeros_like(label)
        else:
            lab_ct = np.zeros(label.shape, dtype=jax.dtypes.float0)
        return (grad.astype(data.dtype), lab_ct)

    fwd.defvjp(f, b)
    return fwd


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    key = (float(margin), float(regularization_coefficient), bool(use_linear))
    fn = _svm_output_cache.get(key)
    if fn is None:
        fn = _make_svm_output(*key)
        _svm_output_cache[key] = fn
    return fn(data, label)


@register("im2col")
def im2col(data, kernel=(), stride=(), dilate=(), pad=()):
    """Sliding-window unfold: (B,C,*sp) -> (B, C*prod(kernel), L)
    (reference src/operator/nn/im2col.h).  Feature order is channel-major
    then kernel-position, matching the reference."""
    n = len(kernel)
    stride = _pair(stride or 1, n)
    dilate = _pair(dilate or 1, n)
    pad = _pair(pad or 0, n)
    patches = jax.lax.conv_general_dilated_patches(
        data, filter_shape=tuple(int(k) for k in kernel),
        window_strides=tuple(int(s) for s in stride),
        padding=[(int(p), int(p)) for p in pad],
        rhs_dilation=tuple(int(d) for d in dilate))
    B = data.shape[0]
    return patches.reshape(B, patches.shape[1], -1)


@register("col2im")
def col2im(data, output_size=(), kernel=(), stride=(), dilate=(), pad=()):
    """Inverse of im2col: overlapping patches scatter-add back into
    (B, C, *output_size) (reference src/operator/nn/im2col.h col2im)."""
    n = len(kernel)
    stride = _pair(stride or 1, n)
    dilate = _pair(dilate or 1, n)
    pad = _pair(pad or 0, n)
    kernel = tuple(int(k) for k in kernel)
    out_sp = tuple(int(s) for s in output_size)
    B = data.shape[0]
    C = data.shape[1] // int(np.prod(kernel))
    padded_sp = tuple(out_sp[i] + 2 * int(pad[i]) for i in range(n))
    o_sp = tuple(
        (padded_sp[i] - (dilate[i] * (kernel[i] - 1) + 1)) // stride[i] + 1
        for i in range(n))
    cols = data.reshape((B, C) + kernel + o_sp)
    out = jnp.zeros((B, C) + padded_sp, jnp.float32)
    for kidx in np.ndindex(*kernel):
        sl = tuple(
            slice(kidx[i] * dilate[i],
                  kidx[i] * dilate[i] + o_sp[i] * stride[i], stride[i])
            for i in range(n))
        out = out.at[(slice(None), slice(None)) + sl].add(
            cols[(slice(None), slice(None)) + kidx].astype(jnp.float32))
    crop = tuple(slice(int(pad[i]), int(pad[i]) + out_sp[i])
                 for i in range(n))
    return out[(slice(None), slice(None)) + crop].astype(data.dtype)


@register("RNN")
def rnn_op(data, parameters, state, *state_cell, state_size=0, num_layers=1,
           bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
           projection_size=None, use_sequence_length=False, lstm_state_clip_min=None,
           lstm_state_clip_max=None, lstm_state_clip_nan=False):
    """Fused RNN with the reference's packed flat parameter vector
    (reference src/operator/rnn.cc: weights layer-major i2h/h2h first,
    then all biases — the cuDNN/MIOpen packing).  Unpacks the vector and
    delegates to rnn_ops._fused_rnn.  Dropout between layers is
    inference-ignored here (the stateless op has no RNG key input);
    gluon.rnn layers use _fused_rnn with an explicit key for training.
    """
    from .rnn_ops import _fused_rnn, rnn_packed_layout

    if use_sequence_length:
        raise MXNetError("RNN: use_sequence_length is not supported; mask "
                         "outputs with SequenceMask instead")
    if (lstm_state_clip_min is not None or lstm_state_clip_max is not None
            or projection_size is not None):
        raise MXNetError("RNN: lstm_state_clip_* / projection_size are not "
                         "supported")

    H = int(state_size)
    dirs = 2 if bidirectional else 1
    flat = parameters
    entries, _ = rnn_packed_layout(mode, data.shape[2], H, num_layers,
                                   bidirectional)
    by_key = {(l, d, g, k): (off, shp) for l, d, g, k, off, shp in entries}

    def take(key):
        off, shp = by_key[key]
        return jax.lax.dynamic_slice_in_dim(
            flat, off, int(np.prod(shp))).reshape(shp)

    weights = []
    for layer in range(num_layers):
        for d in range(dirs):
            weights.extend([take((layer, d, "i2h", "weight")),
                            take((layer, d, "h2h", "weight")),
                            take((layer, d, "i2h", "bias")),
                            take((layer, d, "h2h", "bias"))])
    cell = state_cell[0] if mode == "lstm" else jnp.zeros_like(state)
    outs = _fused_rnn(data, None, state, cell, *weights, mode=mode,
                      state_size=H, num_layers=num_layers,
                      bidirectional=bidirectional, p=0.0, training=False)
    if not state_outputs:
        return outs[0] if isinstance(outs, tuple) else outs
    return outs
