"""Symbol auto-naming scopes (reference: python/mxnet/name.py —
NameManager and Prefix).  ``with mx.name.Prefix('stage1_'):`` prefixes
every auto-generated symbol name created in the scope.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Default manager: pass auto names through unchanged; usable as a
    context manager to scope a custom subclass (reference NameManager)."""

    _current = threading.local()

    def get(self, name, hint):
        """Final name for a node: explicit `name` wins; otherwise derive
        from the auto-generated `hint`."""
        return name if name is not None else hint

    def __enter__(self):
        # stack, not a single slot: reusing one instance in nested/repeated
        # with-blocks must restore correctly
        if not hasattr(self, "_old_stack"):
            self._old_stack = []
        self._old_stack.append(getattr(NameManager._current, "value", None))
        NameManager._current.value = self
        return self

    def __exit__(self, *exc):
        NameManager._current.value = self._old_stack.pop()
        return False


class Prefix(NameManager):
    """Prepend a prefix to every auto-generated name (reference Prefix)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        if name is not None:
            return name
        return self._prefix + hint


def current() -> NameManager:
    mgr = getattr(NameManager._current, "value", None)
    return mgr if mgr is not None else _DEFAULT


_DEFAULT = NameManager()
