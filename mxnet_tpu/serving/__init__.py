"""Inference serving: continuous batching over a paged KV cache
(docs/SERVING.md).

The production answer to "one ``Transformer.translate()`` call per
request": a fixed-slot engine whose hot loop is ONE compiled decode step
shared by ragged in-flight requests (paged KV cache + page tables, per
*Ragged Paged Attention*), a request queue with in-flight admission/
eviction between decode steps, lazy token readback at stream cadence
through the PR 4 ``InflightRing``, AOT-cached executables for
millisecond restarts, and ``serve_request`` SLO telemetry on the PR 2
recorder.
"""
from .paged_cache import (PagedKVCache, PagedStepCache, gather_pages,
                          page_coords, paged_attend, pages_for, write_page)
from .scheduler import (ContinuousBatchingScheduler, Request, TokenStream,
                        queue_bound)
from .engine import (FullPrefixAdapter, ServingAdapter, ServingEngine,
                     TransformerAdapter)

__all__ = ["PagedKVCache", "PagedStepCache", "gather_pages", "page_coords",
           "paged_attend", "pages_for", "write_page",
           "ContinuousBatchingScheduler", "Request", "TokenStream",
           "queue_bound", "ServingAdapter", "ServingEngine",
           "TransformerAdapter", "FullPrefixAdapter"]
