"""Inference serving: continuous batching over a paged KV cache
(docs/SERVING.md).

The production answer to "one ``Transformer.translate()`` call per
request": a fixed-slot engine whose hot loop is ONE compiled decode step
shared by ragged in-flight requests (paged KV cache + page tables, per
*Ragged Paged Attention*), a request queue with in-flight admission/
eviction between decode steps, lazy token readback at stream cadence
through the PR 4 ``InflightRing``, AOT-cached executables for
millisecond restarts, and ``serve_request`` SLO telemetry on the PR 2
recorder.

The front door on top (PR 17): a multi-replica HTTP ``Router`` +
per-engine ``ReplicaServer`` (session affinity, least-outstanding
dispatch, drain/failover), a copy-on-write ``PrefixCache`` sharing
teacher-forced prefix KV pages across requests, real sampling
(temperature / top-k / top-p as traced device ops, seeded per-request
RNG), and speculative decoding (``NGramDraft`` proposes, ONE ragged
("verify", K) dispatch checks).
"""
from .paged_cache import (PagedKVCache, PagedStepCache, gather_pages,
                          page_coords, paged_attend, pages_for, write_page)
from .scheduler import (ContinuousBatchingScheduler, PrefixCache, Request,
                        TokenStream, prefix_key, queue_bound)
from .engine import (FullPrefixAdapter, ServingAdapter, ServingEngine,
                     TransformerAdapter)
from .speculative import DraftProposer, NGramDraft
from .router import (ReplicaServer, Router, discover_replicas,
                     serve_portfile_path)

__all__ = ["PagedKVCache", "PagedStepCache", "gather_pages", "page_coords",
           "paged_attend", "pages_for", "write_page",
           "ContinuousBatchingScheduler", "Request", "TokenStream",
           "queue_bound", "PrefixCache", "prefix_key",
           "ServingAdapter", "ServingEngine",
           "TransformerAdapter", "FullPrefixAdapter",
           "DraftProposer", "NGramDraft",
           "ReplicaServer", "Router", "discover_replicas",
           "serve_portfile_path"]
