"""Continuous-batching inference engine: ONE compiled decode step shared
by ragged in-flight requests (docs/SERVING.md).

The hot loop is a single jitted ``decode_step`` over ``S`` fixed decode
*slots*: every input is shape-stable — per-slot positions, page tables
and validity masks are device VALUES, never shapes — so mixed-length
requests arriving mid-flight reuse one executable with zero per-length
retraces (asserted via memwatch compile events in tests/test_serving.py).
Prefill (encode for seq2seq, prompt ingestion for decoder-only) runs as a
second compiled executable over a fixed padded shape, or folds into the
decode step entirely (``FullPrefixAdapter``).

Dispatch is a lazy pipeline reusing the PR 4 ``InflightRing`` semantics:
``_dispatch_step`` chains device state -> device state and admits one
:class:`~mxnet_tpu.parallel.async_loss.AsyncResult` token handle per step
without ever blocking; the host reads tokens back in bursts of
``MX_SERVE_STREAM_EVERY`` steps (stream cadence — never per token), does
scheduler bookkeeping (EOS -> free the slot's KV pages immediately, admit
waiting requests mid-flight), and dispatches the next burst.

Any model servable here implements :class:`ServingAdapter` — the
"cached-decode interface".  Seeds: :class:`TransformerAdapter`
(models/transformer.py, paged KV decode refactored from its dense cache)
and :class:`FullPrefixAdapter` (any fixed-shape logits function — e.g.
an ONNX-imported decoder-only SymbolBlock — served O(L^2) but still
one-executable).

Both executables AOT-cache through mxnet_tpu.aot_cache (fingerprint
variants ``("decode", page_size, slots)`` / ``("prefill", src_max)``):
with ``MX_EXECUTABLE_CACHE_DIR`` set a serving-process restart
deserializes in milliseconds instead of recompiling.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from .. import aot_cache
from .. import memwatch
from .. import telemetry
from ..base import MXNetError, env_int
from ..parallel.async_loss import AsyncResult, InflightRing
from .paged_cache import PagedKVCache, PagedStepCache, page_coords, pages_for
from .scheduler import (ContinuousBatchingScheduler, PrefixCache, Request,
                        prefix_key)

__all__ = ["ServingAdapter", "TransformerAdapter", "FullPrefixAdapter",
           "ServingEngine"]


def _serve_fused() -> bool:
    """MX_SERVE_FLASH: 'auto' (default) fuses paged attention through the
    Pallas kernel only where it compiles natively (TPU); 1 forces it
    (interpret-mode tests); 0 pins the XLA gather path (the bitwise-
    parity path)."""
    raw = os.environ.get("MX_SERVE_FLASH", "auto").lower()
    if raw in ("0", "false", "off"):
        return False
    if raw in ("1", "true", "on"):
        return True
    from ..ops import pallas

    return pallas.enabled() and pallas.use_compiled()


# ---------------------------------------------------------------------------
# traced sampling math (runs inside the ONE compiled decode/verify step)
# ---------------------------------------------------------------------------
def _filter_logits(logits, temp, topk, topp):
    """Temperature/top-k/top-p filtered logits, per slot (jnp arrays,
    trace-time).  logits (S, V); temp/topp (S,) f32; topk (S,) int32
    (0 = off).  Returns (S, V) logits with masked-out entries at -inf —
    gumbel-argmax over the result samples the truncated, temperature-
    scaled distribution.  Rows with temp == 0 produce garbage here (the
    1e-6 floor) and are discarded by the caller's ``where`` against the
    greedy branch."""
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    order = jnp.argsort(-scaled, axis=-1)
    sdesc = jnp.take_along_axis(scaled, order, axis=-1)
    kk = jnp.clip(jnp.where(topk > 0, topk, V), 1, V).astype(jnp.int32)
    kth = jnp.take_along_axis(sdesc, (kk - 1)[:, None], axis=1)
    filt = jnp.where(scaled < kth, -jnp.inf, scaled)
    # nucleus: drop tokens outside the smallest set whose cumulative
    # (descending) probability reaches top_p; the head token always
    # survives (cum - p_i == 0 < top_p).  top_p >= 1 is a hard off
    # switch — float cumsum can touch 1.0 early and must not truncate.
    fdesc = jnp.take_along_axis(filt, order, axis=-1)
    pdesc = jax.nn.softmax(fdesc, axis=-1)
    cum = jnp.cumsum(pdesc, axis=-1)
    drop_desc = ((cum - pdesc) >= topp[:, None]) & (topp < 1.0)[:, None]
    inv = jnp.argsort(order, axis=-1)
    drop = jnp.take_along_axis(drop_desc, inv, axis=-1)
    return jnp.where(drop, -jnp.inf, filt)


def _split_keys(keys, n):
    """Advance every slot's RNG key one step: (S, 2) uint32 keys ->
    (new_keys (S, 2), subs (S, n, 2)).  Per-slot independent streams —
    a request's randomness is a function of its own seed only, never of
    slot assignment or batch composition."""
    import jax

    out = jax.vmap(lambda k: jax.random.split(k, n + 1))(keys)
    return out[:, 0], out[:, 1:]


def _gumbel_rows(subs, V):
    """(S, 2) subkeys -> (S, V) float32 gumbel noise (one row per slot;
    argmax(logits + gumbel) samples softmax(logits))."""
    import jax
    import jax.numpy as jnp

    return jax.vmap(lambda k: jax.random.gumbel(k, (V,), jnp.float32))(subs)


def _uniform_rows(subs):
    """(S, 2) subkeys -> (S,) float32 U[0,1) — the accept coin flips."""
    import jax
    import jax.numpy as jnp

    return jax.vmap(lambda k: jax.random.uniform(k, (), jnp.float32))(subs)


# ---------------------------------------------------------------------------
# the cached-decode interface
# ---------------------------------------------------------------------------
class ServingAdapter:
    """What a model must expose to be served.

    Attributes: ``num_layers``/``num_heads``/``head_dim`` size the paged
    KV pools (ignored when ``uses_pages`` is False).  All ``F``-taking
    methods run BOTH eagerly and inside the engine's jit trace — NDArray
    ops only, shapes static, values free."""

    uses_pages = True
    num_layers = 0
    num_heads = 1
    head_dim = 1

    def extra_state(self, slots: int, ctx, dtype: str):
        """Adapter-owned device state with a leading slot dim (e.g. the
        encoder memory per slot).  OrderedDict name -> NDArray."""
        return OrderedDict()

    #: extra-state keys the prefill executable produces, in output
    #: order (static — an AOT-cache-hit prefill never traces, so the
    #: names cannot be discovered from the trace)
    prefill_names = ()

    def prefill_src(self, request: Request):
        """Padded (1, Ts) int32 numpy prefill input for the separate
        prefill executable, or None when prefill folds into decode."""
        return None

    def prefill(self, F, src):
        """Traced prefill: (1, Ts) tokens -> dict of extra-state rows
        (each (1, ...)) to install into the request's slot."""
        return {}

    def install(self, state, slot: int, request: Request) -> None:
        """Eager per-slot state init at admission (after core defaults
        tok=bos, pos=0 and any prefill rows are in place)."""

    def validate(self, request: Request) -> None:
        """Reject a request THIS adapter cannot serve, at submit time
        (raise MXNetError).  Anything that would silently truncate or
        corrupt later must fail loudly here."""

    def max_positions(self):
        """The largest decode position the model can represent (e.g. its
        positional-embedding table length), or None for unbounded.  The
        engine refuses a ``max_len`` beyond it at construction — the
        gather-based position lookup would silently CLAMP out-of-table
        positions instead of failing."""
        return None

    def signature(self):
        """Extra structural identity for the AOT-cache fingerprint:
        anything that changes the traced decode program without changing
        shapes (e.g. the fused-attention decision) MUST appear here, or
        a restart could deserialize the wrong executable."""
        return ()

    def warmup(self, ctx) -> None:
        """One tiny eager forward so deferred-init parameters take their
        shapes before the engine traces (gluon Dense layers infer shapes
        on first call)."""

    def decode_logits(self, F, tok, pos, table, keep, pages, rows,
                      lengths, extra, pools):
        """Traced decode of ONE position for every slot, stopping at the
        LOGITS: returns ((S, V) logits, new_extra dict, new_pools list)
        with the KV write applied but NO token selected.  The engine's
        sampling and speculative-verify bodies build on this — greedy
        argmax, temperature sampling and draft acceptance are all
        different selections over the same logits."""
        raise NotImplementedError(
            f"{type(self).__name__} implements neither decode_logits "
            "nor decode — sampling and speculative serving need "
            "decode_logits")

    def advance_extra(self, F, extra, nxt, pos):
        """Apply the CHOSEN token to adapter extra state (traced).  Most
        adapters keep step-invariant extra state (e.g. the encoder
        memory) and inherit this identity; an adapter whose extra state
        records emitted tokens (FullPrefixAdapter's prompt buffer)
        overrides it.  Speculative verify skips this hook — it requires
        the identity behaviour (checked at engine construction)."""
        return extra

    def decode(self, F, tok, pos, table, keep, pages, rows, lengths,
               extra, pools):
        """Traced GREEDY decode of ONE position for every slot.  Returns
        (next_tok (S,) int32, new_extra dict, new_pools list).  The
        default composes :meth:`decode_logits` with the argmax-over-
        log-softmax selection ``translate`` applies at beam_size=1 (the
        bitwise greedy contract) and :meth:`advance_extra`."""
        logits, new_extra, new_pools = self.decode_logits(
            F, tok, pos, table, keep, pages, rows, lengths, extra, pools)
        # argmax over log-softmax, the exact selection translate's beam
        # update applies with beam_size=1 (token-for-token parity)
        nxt = F.cast(F.argmax(logits.log_softmax(axis=-1), axis=-1),
                     "int32")
        new_extra = self.advance_extra(F, new_extra, nxt, pos)
        return nxt, new_extra, new_pools


class TransformerAdapter(ServingAdapter):
    """models/transformer.py seq2seq decode on the paged KV cache.

    Prefill = the encoder over the source padded to ``src_max_len``
    (one compiled prefill regardless of source length); decode = the
    same ``Transformer._decode_step`` the standalone ``translate`` runs,
    greedy (log-softmax argmax — matches ``translate(beam_size=1)``
    token-for-token)."""

    prefill_names = ("mem", "src_keep")

    def __init__(self, model, src_max_len: int, fused: Optional[bool] = None):
        self.model = model
        self.src_max = int(src_max_len)
        sa = model.decoder.layers[0].self_attn
        self.num_layers = len(model.decoder.layers)
        self.num_heads = sa._num_heads
        self.head_dim = sa._head_dim
        self._fused = fused

    def _resolved_fused(self) -> bool:
        """The fused decision, resolved ONCE and pinned — the traced
        program and the AOT-cache fingerprint must agree on it."""
        if self._fused is None:
            self._fused = _serve_fused()
        return self._fused

    def max_positions(self):
        return self.model.pos._max_length

    def signature(self):
        return ("fused", self._resolved_fused())

    def extra_state(self, slots, ctx, dtype):
        from ..ndarray import zeros as nd_zeros

        units = self.model._units
        return OrderedDict(
            mem=nd_zeros((slots, self.src_max, units), ctx=ctx,
                         dtype=dtype),
            src_keep=nd_zeros((slots, self.src_max), ctx=ctx, dtype=dtype))

    def validate(self, request):
        if request.tokens.shape[0] > self.src_max:
            raise MXNetError(
                f"request {request.id} source length "
                f"{request.tokens.shape[0]} > adapter src_max_len "
                f"{self.src_max}")

    def prefill_src(self, request):
        toks = request.tokens
        self.validate(request)
        row = np.full((1, self.src_max), self.model._pad_id, np.int32)
        row[0, :toks.shape[0]] = toks
        return row

    def prefill(self, F, src):
        mem, src_keep = self.model._encode_h(F, src)
        return {"mem": mem, "src_keep": src_keep}

    def warmup(self, ctx):
        from ..ndarray import array as nd_array

        src = np.full((1, self.src_max), self.model._pad_id, np.int32)
        src[0, 0] = 1
        tgt = np.ones((1, 1), np.int32)
        self.model(nd_array(src, ctx=ctx, dtype="int32"),
                   nd_array(tgt, ctx=ctx, dtype="int32"))

    def decode_logits(self, F, tok, pos, table, keep, pages, rows,
                      lengths, extra, pools):
        fused = self._resolved_fused()
        caches = [PagedStepCache(pools[2 * i], pools[2 * i + 1], table,
                                 pages, rows, keep,
                                 lengths=lengths, fused=fused)
                  for i in range(self.num_layers)]
        logits = self.model._decode_step(F, tok, pos, extra["mem"],
                                         extra["src_keep"], caches)
        new_pools = []
        for c in caches:
            new_pools.extend((c.k_pool, c.v_pool))
        return logits, extra, new_pools


class FullPrefixAdapter(ServingAdapter):
    """Serve ANY fixed-shape decoder-only logits function — prefill
    chunked into the decode step (the prompt sits in the slot's token
    buffer; the first decode computes it along with everything else).

    ``logits_fn(F, buf) -> (S, L, V)`` over the (S, L) int32 token
    buffer; e.g. a causal HybridBlock forward or an ONNX-imported
    decoder.  O(L^2) per generated token (the universal fallback — no KV
    cache assumptions), but still shape-stable: ONE executable for every
    request length."""

    uses_pages = False

    def __init__(self, logits_fn, max_len: int, pad_id: int = 0):
        self._fn = logits_fn
        self.max_len = int(max_len)
        self.pad_id = int(pad_id)

    def extra_state(self, slots, ctx, dtype):
        from ..ndarray import zeros as nd_zeros

        return OrderedDict(
            buf=nd_zeros((slots, self.max_len), ctx=ctx, dtype="int32"))

    def validate(self, request):
        need = request.tokens.shape[0] + request.max_new_tokens
        if need > self.max_len:
            raise MXNetError(
                f"request {request.id} needs {need} buffer positions "
                f"(prompt {request.tokens.shape[0]} + max_new "
                f"{request.max_new_tokens}) > adapter max_len "
                f"{self.max_len} — the fixed prefix buffer would "
                "silently truncate")

    def install(self, state, slot, request):
        row = np.full((self.max_len,), self.pad_id, np.int32)
        n = request.tokens.shape[0]
        row[:n] = request.tokens
        state["buf"][slot] = row
        state["pos"][slot] = max(0, n - 1)

    def decode_logits(self, F, tok, pos, table, keep, pages, rows,
                      lengths, extra, pools):
        from ..ndarray import NDArray
        import jax.numpy as jnp

        buf = extra["buf"]
        logits = self._fn(F, buf)                      # (S, L, V)
        step = jnp.take_along_axis(
            logits._data, pos._data[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]                              # (S, V)
        return NDArray(step, ctx=buf.context), extra, []

    def advance_extra(self, F, extra, nxt, pos):
        from ..ndarray import NDArray
        import jax.numpy as jnp

        buf = extra["buf"]
        S, L = buf.shape
        wpos = jnp.minimum(pos._data + 1, L - 1)
        new_buf = NDArray(
            buf._data.at[jnp.arange(S), wpos].set(nxt._data),
            ctx=buf.context)
        return {"buf": new_buf}


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------
class _Active:
    """Host bookkeeping of one occupied slot."""

    __slots__ = ("req", "pos", "done", "seq")

    def __init__(self, req: Request, seq: int):
        self.req = req
        self.pos = 0      # mirrors the slot's DEVICE position counter
        self.done = False
        self.seq = seq    # admission order (preemption evicts youngest)


class ServingEngine:
    """Fixed-slot continuous-batching engine over one compiled decode
    step (module docstring has the architecture; docs/SERVING.md the
    knobs)."""

    def __init__(self, adapter: ServingAdapter, slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 pool_pages: Optional[int] = None, max_len: int = 64,
                 stream_every: Optional[int] = None,
                 queue_bound: Optional[int] = None, ctx=None,
                 dtype: str = "float32",
                 sampling: Optional[bool] = None,
                 spec_k: Optional[int] = None, draft=None,
                 prefix_cache: Optional[bool] = None,
                 prefix_entries: Optional[int] = None):
        from ..context import current_context
        from ..ndarray import zeros as nd_zeros

        self._adapter = adapter
        # ---- front-door features, all default-OFF (parity-pinned):
        # sampling adds per-slot temp/topk/topp/rng device state and a
        # sampled decode body; spec_k > 0 switches the run loop to
        # draft-propose + one ("verify", K) dispatch per boundary;
        # prefix_cache turns on COW page sharing + prefill-row reuse.
        self._sampling = (env_int("MX_SERVE_SAMPLING", 0) != 0
                          if sampling is None else bool(sampling))
        self._spec_k = max(0, spec_k if spec_k is not None
                           else env_int("MX_SERVE_SPEC_K", 0))
        if self._spec_k and not adapter.uses_pages:
            raise MXNetError(
                "speculative decoding (spec_k > 0) needs a paged-KV "
                "adapter — the verify step teacher-forces K positions "
                "through the paged cache")
        self._draft = draft
        if self._spec_k and self._draft is None:
            from .speculative import NGramDraft

            self._draft = NGramDraft()
        prefix_on = (env_int("MX_SERVE_PREFIX_CACHE", 0) != 0
                     if prefix_cache is None else bool(prefix_cache))
        if prefix_on and not adapter.uses_pages:
            raise MXNetError(
                "the prefix cache shares paged KV pages — it needs a "
                "paged-KV adapter (uses_pages)")
        self._prefix = PrefixCache(
            prefix_entries if prefix_entries is not None
            else env_int("MX_SERVE_PREFIX_ENTRIES", 64)) \
            if prefix_on else None
        self._prefix_chunk = max(1, env_int("MX_SERVE_PREFIX_CHUNK", 8))
        # precision label of the compiled decode program (fp32, or int8
        # for a precision.QuantizedAdapter) — rides on the mx_serve_*
        # telemetry so dashboards can attribute latency/throughput to
        # the dtype program serving them (docs/PRECISION.md)
        self._precision = str(getattr(adapter, "precision", "fp32"))
        # the serving pass pipeline (passes/builtin.pipeline_for_serving):
        # adapter-contributed quant passes + fused-kernel substitution.
        # Every traced body runs under its scope (_traced), and its ONE
        # signature joins _fingerprint_parts — config/order changes miss
        # the AOT cache instead of loading the wrong program.
        from ..passes.builtin import pipeline_for_serving

        self._pipeline = pipeline_for_serving(adapter)
        self._ctx = ctx if ctx is not None else current_context()
        self._S = slots if slots is not None else env_int("MX_SERVE_SLOTS", 8)
        self._ps = page_size if page_size is not None \
            else env_int("MX_SERVE_PAGE_SIZE", 16)
        self._max_len = int(max_len)
        self._stream_every = max(1, stream_every if stream_every is not None
                                 else env_int("MX_SERVE_STREAM_EVERY", 4))
        self._dtype = dtype
        cap = adapter.max_positions()
        if cap is not None and self._max_len > cap:
            raise MXNetError(
                f"engine max_len {self._max_len} > the model's "
                f"max_positions {cap} (positional table) — out-of-table "
                "positions would silently clamp; lower max_len or build "
                "the model with a larger max_length")
        if adapter.uses_pages:
            n_pages = pool_pages if pool_pages is not None \
                else env_int("MX_SERVE_POOL_PAGES", 0)
            if not n_pages:  # auto: every slot can reach max_len
                n_pages = self._S * pages_for(self._max_len, self._ps) + 1
            self._cache = PagedKVCache(
                adapter.num_layers, n_pages, self._ps, adapter.num_heads,
                adapter.head_dim, ctx=self._ctx, dtype=dtype)
            # table wide enough that positions overrun by a full burst
            # (a request finishing mid-burst keeps decoding until the
            # stream boundary) land on zero -> trash page, never clamp
            # into a live page; a speculative verify overruns by up to
            # K+1 positions per boundary, whichever is larger
            overrun = max(self._stream_every, self._spec_k + 1)
            self._P = pages_for(self._max_len + overrun, self._ps)
        else:
            self._cache = None
            self._P = 1
        self._sched = ContinuousBatchingScheduler(queue_bound)
        self._ring = InflightRing("ServingEngine")
        self._slots: List[Optional[_Active]] = [None] * self._S
        self._arrivals: List = []  # (arrive_at_step, request), sorted
        self._step_n = 0
        self._admit_seq = 0

        # device state: core (tok/pos/table) + adapter extra + pools;
        # everything the compiled step threads state -> state
        state = OrderedDict(
            tok=nd_zeros((self._S, 1), ctx=self._ctx, dtype="int32"),
            pos=nd_zeros((self._S,), ctx=self._ctx, dtype="int32"),
            table=nd_zeros((self._S, self._P), ctx=self._ctx,
                           dtype="int32"))
        # per-slot sampling state rides the compiled step ONLY when
        # sampling is on: a greedy engine's state (and therefore its
        # traced program and AOT fingerprint) is unchanged — the
        # parity-pinned default
        self._samp_names: List[str] = []
        if self._sampling:
            state["temp"] = nd_zeros((self._S,), ctx=self._ctx,
                                     dtype="float32")
            state["topk"] = nd_zeros((self._S,), ctx=self._ctx,
                                     dtype="int32")
            state["topp"] = nd_zeros((self._S,), ctx=self._ctx,
                                     dtype="float32")
            state["rng"] = nd_zeros((self._S, 2), ctx=self._ctx,
                                    dtype="uint32")
            self._samp_names = ["temp", "topk", "topp", "rng"]
        extra = adapter.extra_state(self._S, self._ctx, dtype)
        self._extra_names = list(extra)
        state.update(extra)
        self._pool_names: List[str] = []
        if self._cache is not None:
            for i, (kp, vp) in enumerate(self._cache.pools):
                state[f"kpool{i}"] = kp
                state[f"vpool{i}"] = vp
                self._pool_names += [f"kpool{i}", f"vpool{i}"]
        self._state = state
        self._names = list(state)

        self._param_items = None
        self._run = None
        self._vrun = None   # ("verify", K) speculative executable
        self._irun = None   # ("ingest", K) prefix teacher-forcing
        self._last_nprop = None
        self._spec_proposed = 0  # lifetime draft tokens proposed
        self._spec_accepted = 0  # lifetime draft tokens accepted
        self._prefill_run = None
        self._prefill_names: List[str] = []
        self._pending_compile: Dict = {}
        # zero-downtime weight hot-swap (docs/SERVING.md §Weight
        # hot-swap): verified new weights wait in _staging until the run
        # loop flips them in at a stream boundary
        self._staging: Dict[str, np.ndarray] = {}
        self._swap_pending: Optional[dict] = None
        self._swap_lock = threading.Lock()
        self._running = False
        self._weight_generation = 0
        # live-array census category for the watchdog: the paged pools +
        # slot state are the serving engine's resident footprint
        memwatch.register("serving", self,
                          lambda eng: [a._data for a in
                                       eng._state.values()])
        # the swap staging buffer is its own census category: the
        # transient 2x-weights window shows up attributed (and the leak
        # detector never mistakes it for growth) — it must read empty
        # again after the flip
        memwatch.register("staging", self,
                          lambda eng: list(eng._staging.values()))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, request: Request) -> Request:
        plen = int(request.prefix.size)
        if plen + request.max_new_tokens > self._max_len:
            raise MXNetError(
                f"request {request.id} prefix {plen} + max_new_tokens "
                f"{request.max_new_tokens} > engine max_len "
                f"{self._max_len}")
        if plen and not self._adapter.uses_pages:
            raise MXNetError(
                f"request {request.id} carries a decoder prefix but the "
                "adapter has no paged KV cache to teacher-force it into "
                "— fold the prefix into the prompt instead")
        if request.temperature > 0 and not self._sampling:
            raise MXNetError(
                f"request {request.id} asks for temperature "
                f"{request.temperature} but this engine was built "
                "greedy-only — construct ServingEngine(sampling=True) "
                "or set MX_SERVE_SAMPLING=1")
        self._adapter.validate(request)
        return self._sched.submit(request)

    def serve(self, requests, arrival_steps=None) -> Dict[str, np.ndarray]:
        """Decode ``requests`` to completion; returns {id: tokens}.

        ``arrival_steps`` (optional, aligned with ``requests``) delays
        request i until the engine's global decode-step counter reaches
        that value — mid-flight joins, the continuous-batching test
        surface.  Requests with arrival 0/None submit immediately."""
        requests = list(requests)
        if arrival_steps is None:
            arrival_steps = [0] * len(requests)
        base = self._step_n
        for req, at in zip(requests, arrival_steps):
            if at:
                self._arrivals.append((base + int(at), req))
            else:
                self.submit(req)
        self._arrivals.sort(key=lambda p: p[0])
        self.run()
        return {r.id: r.stream.asarray() for r in requests}

    def swap_weights(self, ckpt_dir: str,
                     step: Optional[int] = None) -> int:
        """Zero-downtime weight hot-swap: load a checkpoint's params into
        a STAGING buffer off the decode path, verify them, and flip the
        served param pytree at the next stream boundary — in-flight
        requests finish against a consistent weight set, the paged KV
        pool and page tables are untouched, and because ``_params()`` is
        re-read live each dispatch the compiled decode executable is
        reused as-is (same AOT fingerprint = zero recompile).

        Verification before anything is published: the checkpoint's
        SHA-256 digests (``load_checkpoint_state`` rejects torn/corrupt
        steps), full param coverage, and the decode AOT fingerprint
        recomputed over the staged arrays — a mismatched fingerprint
        (different shapes/dtypes, i.e. a different/quantized model) is a
        LOUD rejection and the engine keeps serving the old weights.

        Thread-safe against a concurrent :meth:`run`: the flip itself
        only ever happens on the run-loop thread (between decode bursts)
        or synchronously here when the engine is idle.  Returns the
        checkpoint step swapped in; telemetry records a ``weight_swap``
        event (staged bytes, verify/flip ms, generation) surfaced in
        ``/statusz`` and ``mx_serve_weight_generation``."""
        from .. import checkpoint as ckpt_mod

        t0 = time.perf_counter()
        self._ensure_compiled()
        state = ckpt_mod.load_checkpoint_state(ckpt_dir, step=step)
        if state is None:
            raise MXNetError(
                f"swap_weights: no valid checkpoint in {ckpt_dir!r} — "
                "keeping the current weights")
        snap = state["params"]
        model = getattr(self._adapter, "model", None)
        by_param = {}
        if model is not None and hasattr(model,
                                         "_collect_params_with_prefix"):
            by_param = {id(p): s for s, p in
                        model._collect_params_with_prefix().items()}
        staging: Dict[str, np.ndarray] = {}
        try:
            for name, p in self._param_items:
                sname = by_param.get(id(p), name)
                if sname not in snap:
                    raise MXNetError(
                        f"swap_weights: checkpoint step {state['step']} "
                        f"is missing parameter {sname!r} — rejected, "
                        "keeping the current weights")
                v = snap[sname]
                staging[name] = (v.asnumpy() if hasattr(v, "asnumpy")
                                 else np.asarray(v))
            # the fingerprint gate: the decode executable's structural
            # identity recomputed over the STAGED arrays must equal the
            # serving one — same structure means the compiled step (and
            # any AOT cache entry) keeps working unchanged
            variant = ("decode", self._ps, self._S)
            sarrs = [a._data for a in self._state.values()]
            cur = memwatch.fingerprint(self._fingerprint_parts(
                variant, list(self._params()) + sarrs))
            new = memwatch.fingerprint(self._fingerprint_parts(
                variant, [staging[n] for n, _ in self._param_items]
                + sarrs))
            if new != cur:
                raise MXNetError(
                    f"swap_weights: checkpoint step {state['step']} has "
                    "a different decode fingerprint (param shapes/dtypes "
                    "or adapter structure changed) — rejected, keeping "
                    "the current weights")
        except MXNetError as e:
            staging.clear()
            telemetry.record("weight_swap", executor="ServingEngine",
                             rejected=True, reason=str(e),
                             generation=self._weight_generation)
            raise
        verify_ms = (time.perf_counter() - t0) * 1e3
        with self._swap_lock:
            self._staging = staging
            self._swap_pending = {
                "step": int(state["step"]),
                "staged_bytes": int(sum(a.nbytes
                                        for a in staging.values())),
                "verify_ms": verify_ms,
            }
        if not self._running:
            # idle engine: no stream boundary will come around — flip now
            self._apply_pending_swap()
        return int(state["step"])

    def _apply_pending_swap(self) -> None:
        """Flip staged weights into the served params (stream-boundary
        only: the run loop between bursts, or swap_weights on an idle
        engine).  ``_params()`` reads ``p.data()`` live each dispatch, so
        set_data IS the flip — the compiled executable never changes."""
        with self._swap_lock:
            pending, staging = self._swap_pending, self._staging
            self._swap_pending = None
            if pending is None:
                return
        t0 = time.perf_counter()
        for name, p in self._param_items:
            p.set_data(staging[name])
        self._weight_generation += 1
        # swap-aware prefix-cache invalidation: every cached prefix was
        # stamped with the generation it was computed under; at the flip
        # all older entries drop (and release their pages) BEFORE the
        # next admission can fork them — a post-swap request can never
        # decode against old-weight KV pages (tests/test_serving_swap).
        if self._prefix is not None:
            dropped = self._prefix.invalidate_stale(self._weight_generation)
            for e in dropped:
                self._release_prefix_entry(e)
            if dropped:
                telemetry.record(
                    "serve_prefix_invalidate", executor="ServingEngine",
                    dropped=len(dropped),
                    generation=self._weight_generation)
        # drain the staging census: post-flip the transient 2x-weights
        # window is over and memwatch's "staging" category reads empty
        self._staging = {}
        telemetry.record_weight_swap(
            generation=self._weight_generation,
            staged_bytes=pending["staged_bytes"],
            verify_ms=pending["verify_ms"],
            flip_ms=(time.perf_counter() - t0) * 1e3,
            step=pending["step"])

    @property
    def weight_generation(self) -> int:
        """How many hot-swaps have been applied (0 = boot weights)."""
        return self._weight_generation

    def run(self, max_steps: int = 1_000_000) -> None:
        """Drive the engine until queue, arrivals and slots are empty."""
        self._ensure_compiled()
        guard = 0
        spins = 0
        self._running = True
        try:
            while True:
                self._pump_arrivals()
                admitted = self._admit_ready()
                active = sum(1 for m in self._slots if m is not None)
                if not active:
                    if self._arrivals:
                        # idle: fast-forward the step clock to the next
                        # join
                        self._step_n = max(self._step_n,
                                           self._arrivals[0][0])
                        continue
                    if self._sched.depth:
                        # all slots free, none admitted: tolerate ONE
                        # spin — a concurrent submit (the replica
                        # server's handler threads) can land between
                        # _admit_ready and the depth check; a request
                        # that truly cannot fit fails again next pass
                        spins += 1
                        if spins > 1:
                            raise MXNetError(
                                "serving queue non-empty but no request "
                                "admissible (pool/config too small?)")
                        continue
                    break
                spins = 0
                spec = self._spec_k > 0 and self._cache is not None
                want = self._spec_k + 1 if spec else self._stream_every
                burst = self._ensure_pages(want)
                # request ids decoding THIS burst (with their trace
                # context), captured before _consume can evict
                # finished ones
                burst_ids = [(m.req.id, m.req.trace_id, m.req.sampled)
                             for m in self._slots
                             if m is not None and not m.done]
                t_burst0 = time.perf_counter()
                if spec and burst == self._spec_k + 1:
                    # one ragged verify dispatch per boundary: draft
                    # proposes K, the target checks all K (+ bonus) in
                    # ONE compiled step; per-slot accepted counts are
                    # device values
                    self._ensure_verify()
                    handle, counts_dev = self._dispatch_spec()
                    self._book_pending_compile()
                    t_stream0 = time.perf_counter()
                    self._consume_spec(handle, counts_dev)
                    burst = self._spec_k + 1  # guard accounting
                else:
                    # plain path (also the fallback when pool pressure
                    # or a near-budget request shrinks the burst below
                    # the verify window)
                    handles = [self._dispatch_step()
                               for _ in range(burst)]
                    self._book_pending_compile()
                    t_stream0 = time.perf_counter()
                    self._consume(handles)
                t_stream1 = time.perf_counter()
                # per-request trace spans at BURST cadence, never per
                # token (docs/OBSERVABILITY.md §Serving traces): one
                # serve_decode span per in-flight request covering
                # dispatch through token readback, plus one serve_stream
                # span for the readback boundary carrying the occupancy
                # gauges trace_report turns into the slot-occupancy
                # timeline.  record_span is the zero-cost-when-off
                # retroactive form — the dispatch loop above never pays
                # for tracing.
                if telemetry.spans_enabled():
                    for rid, tid, samp in burst_ids:
                        if tid is not None and not samp:
                            continue  # head-based sampling dropped it
                        telemetry.record_span(
                            "serve_decode", t_burst0, t_stream1,
                            request_id=rid, steps=burst,
                            **({"trace_id": tid} if tid else {}))
                    telemetry.record_span("serve_stream", t_stream0,
                                          t_stream1,
                                          active_slots=len(burst_ids),
                                          queue_depth=self._sched.depth)
                telemetry.record_serve_state(queue_depth=self._sched.depth,
                                             active_slots=active,
                                             precision=self._precision)
                if self._swap_pending is not None:
                    # the stream boundary IS the swap point: this burst's
                    # tokens are consumed, nothing is in flight — the
                    # next burst dispatches against the new weights
                    self._apply_pending_swap()
                guard += burst
                if guard > max_steps:
                    raise MXNetError(
                        f"serving run exceeded {max_steps} decode "
                        "steps (runaway request set?)")
        finally:
            self._running = False
        self._ring.drain()

    @property
    def step_count(self) -> int:
        return self._step_n

    # ------------------------------------------------------------------
    # compiled step construction
    # ------------------------------------------------------------------
    def _params(self):
        if self._param_items is None:
            model = getattr(self._adapter, "model", None)
            self._param_items = (list(model.collect_params().items())
                                 if model is not None else [])
        return tuple(p.data(self._ctx)._data for _, p in self._param_items)

    def _traced(self, body):
        """Run ``body`` under the parameter-substitution trace (the
        CachedOp recipe): model code sees traced param values, dropout/BN
        stay in inference mode."""
        from .. import autograd
        from ..gluon.parameter import begin_trace, end_trace

        def fn(param_arrays, *arrays):
            from ..ndarray import NDArray

            param_map = {p: NDArray(a, ctx=self._ctx)
                         for (_, p), a in zip(self._param_items,
                                              param_arrays)}
            nds = [NDArray(a, ctx=self._ctx) for a in arrays]
            prev = begin_trace(param_map, self._ctx)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(False)
            try:
                # every serving executable traces under the pass
                # pipeline's scope (quant rewrites, fused-kernel
                # substitution) — one place, all variants
                with self._pipeline.scope():
                    out = body(nds)
            finally:
                end_trace(prev)
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
            return tuple(o._data for o in out)

        return fn

    def _decode_body(self, nds):
        from .. import ndarray as F
        from ..ndarray import NDArray
        import jax.numpy as jnp

        state = dict(zip(self._names, nds))
        tok, pos, table = state["tok"], state["pos"], state["table"]
        lengths = pos + 1  # rows valid incl. the one written this step
        Lmax = self._P * self._ps
        keep = NDArray(
            (jnp.arange(Lmax, dtype=jnp.float32)[None, :]
             < lengths._data.astype(jnp.float32)[:, None])
            .astype(jnp.float32), ctx=self._ctx)
        pages, rows = page_coords(table, pos, self._ps)
        extra = {k: state[k] for k in self._extra_names}
        pools = [state[k] for k in self._pool_names]
        new_state = dict(state)
        if not self._sampling:
            # the original greedy body, op-for-op (the parity-pinned
            # default: same trace, same AOT fingerprint)
            nxt, new_extra, new_pools = self._adapter.decode(
                F, tok, pos, table, keep, pages, rows, lengths, extra,
                pools)
        else:
            logits, new_extra, new_pools = self._adapter.decode_logits(
                F, tok, pos, table, keep, pages, rows, lengths, extra,
                pools)
            nxt, new_state["rng"] = self._select_token(F, state, logits)
            new_extra = self._adapter.advance_extra(F, new_extra, nxt,
                                                    pos)
        new_state["tok"] = nxt.reshape(self._S, 1)
        new_state["pos"] = pos + 1
        new_state.update(new_extra)
        new_state.update(dict(zip(self._pool_names, new_pools)))
        return (nxt,) + tuple(new_state[k] for k in self._names)

    def _select_token(self, F, state, logits):
        """Traced token selection under sampling.  Slots with
        temperature 0 take the EXACT argmax-over-log-softmax op sequence
        the greedy body traces — ``where`` selects per slot, so a greedy
        request in a sampling engine stays bitwise identical to the
        greedy engine (tests/test_serving_sampling).  Sampling slots
        take gumbel-argmax over the temperature/top-k/top-p-filtered
        logits, with per-slot RNG keys advanced as device state."""
        from ..ndarray import NDArray
        import jax.numpy as jnp

        greedy = F.cast(F.argmax(logits.log_softmax(axis=-1), axis=-1),
                        "int32")
        temp = state["temp"]._data
        filt = _filter_logits(logits._data, temp, state["topk"]._data,
                              state["topp"]._data)
        new_keys, subs = _split_keys(state["rng"]._data, 1)
        g = _gumbel_rows(subs[:, 0], filt.shape[-1])
        sampled = jnp.argmax(filt + g, axis=-1).astype(jnp.int32)
        nxt = jnp.where(temp > 0, sampled, greedy._data)
        return (NDArray(nxt, ctx=self._ctx),
                NDArray(new_keys, ctx=self._ctx))

    def _shape_sig(self, arrays):
        return tuple((tuple(np.shape(a)), str(getattr(a, "dtype", "?")))
                     for a in arrays)

    def _fingerprint_parts(self, variant, arg_arrays):
        """Restart-stable structural identity (the memwatch.fingerprint /
        aot_cache key contract — shapes/dtypes/config, no object ids)."""
        model = getattr(self._adapter, "model", None)
        return (("ServingEngine",) + tuple(variant)
                + (type(self._adapter).__name__,
                   type(model).__name__ if model is not None else "",
                   tuple(self._adapter.signature()),
                   self._pipeline.signature(),
                   self._S, self._ps, self._P, self._max_len,
                   self._shape_sig(arg_arrays)))

    def _resolve(self, jfn, args, variant, site):
        """AOT-resolve one executable through the persistent cache;
        falls back to plain jit dispatch (compile booked at first call
        via ``_pending_compile``)."""
        # fingerprint over params + operands
        flat = list(args[0]) + list(args[1:])
        parts = self._fingerprint_parts(variant, flat)
        dev = self._ctx.jax_device
        t0 = time.perf_counter()
        compiled, info = aot_cache.get_or_compile(
            jfn, args, fingerprint=memwatch.fingerprint(parts),
            platform=dev.platform, mesh_shape=(),
            device_ids=(int(dev.id),))
        if compiled is not None:
            memwatch.note_compile(
                "ServingEngine", parts,
                wall_s=time.perf_counter() - t0, site=site,
                jitted=None if info.get("cache_hit") else jfn,
                args=memwatch.shape_structs(args), **info)
            return compiled
        self._pending_compile[site] = {"parts": parts, "jitted": jfn,
                                       "args": memwatch.shape_structs(args)}
        return jfn

    def _ensure_compiled(self):
        if self._run is not None:
            return
        import jax

        self._adapter.warmup(self._ctx)  # deferred-init shapes first
        self._params()  # resolve the param list before tracing
        jfn = jax.jit(self._traced(self._decode_body))
        args = (self._params(),) + tuple(a._data
                                         for a in self._state.values())
        self._run = self._resolve(jfn, args,
                                  ("decode", self._ps, self._S),
                                  "serving_decode")

    def _ensure_prefill(self, src_row):
        if self._prefill_run is not None:
            return
        import jax

        adapter = self._adapter
        self._prefill_names = list(adapter.prefill_names)

        def body(nds):
            from .. import ndarray as F

            out = adapter.prefill(F, nds[0])
            return [out[k] for k in adapter.prefill_names]

        jfn = jax.jit(self._traced(body))
        import jax.numpy as jnp

        args = (self._params(), jnp.asarray(src_row))
        self._prefill_run = self._resolve(
            jfn, args, ("prefill", src_row.shape[1]), "serving_prefill")

    # ------------------------------------------------------------------
    # teacher-forced multi-position bodies: speculative verify + prefix
    # ingest.  Both unroll K(+1) decode_logits bodies inside ONE jitted
    # step — per-slot proposal counts / ingest lengths are device
    # values, so the SAME executable serves every ragged mix (the
    # ragged-paged-attention property, applied along the position axis).
    #
    # KV safety: body j writes position pos+j BEFORE attending lengths
    # pos+j+1, so rows past a slot's accepted/ingested count hold
    # teacher-forced garbage — but the next dispatch starts at the
    # slot's new pos and REWRITES each such row before it is ever
    # attended (the same invariant the plain decode loop relies on for
    # freshly-granted pages).  Writes beyond a slot's granted pages
    # land on the zero table entry -> trash page.
    # ------------------------------------------------------------------
    def _chain_logits(self, F, state, feed, steps):
        """Unroll ``steps`` decode_logits bodies, teacher-forcing
        ``feed[:, j]`` at position pos+j.  Returns (logits list,
        final extra, final pools) — trace-time only."""
        from ..ndarray import NDArray
        import jax.numpy as jnp

        pos, table = state["pos"], state["table"]
        extra = {k: state[k] for k in self._extra_names}
        pools = [state[k] for k in self._pool_names]
        Lmax = self._P * self._ps
        out = []
        for j in range(steps):
            pos_j = pos + j
            lengths = pos_j + 1
            keep = NDArray(
                (jnp.arange(Lmax, dtype=jnp.float32)[None, :]
                 < lengths._data.astype(jnp.float32)[:, None])
                .astype(jnp.float32), ctx=self._ctx)
            pages, rows = page_coords(table, pos_j, self._ps)
            tok_j = NDArray(feed[:, j:j + 1], ctx=self._ctx)
            logits, extra, pools = self._adapter.decode_logits(
                F, tok_j, pos_j, table, keep, pages, rows, lengths,
                extra, pools)
            out.append(logits)
        return out, extra, pools

    def _verify_body(self, nds):
        """The ("verify", K) executable: teacher-force [tok, d_1..d_K]
        through K+1 decode bodies, accept the longest draft prefix the
        target agrees with (argmax equality under greedy; the standard
        u < p(d) test under sampling), emit a correction/bonus token
        from the first disagreeing position, and advance per-slot state
        by the ACCEPTED count — a device value.  Greedy rows are
        token-for-token the plain decode stream; sampling rows draw
        from exactly the non-speculative output distribution
        (accept/resample, Leviathan et al.)."""
        from .. import ndarray as F
        from ..ndarray import NDArray
        import jax
        import jax.numpy as jnp

        K = self._spec_k
        S = self._S
        n_state = len(self._names)
        state = dict(zip(self._names, nds[:n_state]))
        draft, nprop = nds[n_state], nds[n_state + 1]
        tok, pos = state["tok"], state["pos"]
        d = draft._data                                   # (S, K)
        feed = jnp.concatenate([tok._data, d], axis=1)    # (S, K+1)
        logits_l, extra, pools = self._chain_logits(F, state, feed, K + 1)
        greedy = jnp.stack(
            [F.cast(F.argmax(lg.log_softmax(axis=-1), axis=-1),
                    "int32")._data for lg in logits_l], axis=1)  # (S,K+1)
        kclip = jnp.clip(nprop._data, 0, K)               # (S,)
        jj = jnp.arange(K, dtype=jnp.int32)[None, :]
        if self._sampling:
            temp = state["temp"]._data
            filt = jnp.stack(
                [_filter_logits(lg._data, temp, state["topk"]._data,
                                state["topp"]._data)
                 for lg in logits_l], axis=1)             # (S, K+1, V)
            V = filt.shape[-1]
            new_keys, subs = _split_keys(state["rng"]._data, 2 * K + 1)
            u = jnp.stack([_uniform_rows(subs[:, j])
                           for j in range(K)], axis=1) if K else \
                jnp.zeros((S, 0), jnp.float32)            # (S, K)
            gum = jnp.stack([_gumbel_rows(subs[:, K + j], V)
                             for j in range(K + 1)], axis=1)  # (S,K+1,V)
            probs = jax.nn.softmax(filt, axis=-1)
            pd = jnp.take_along_axis(
                probs[:, :K], d[..., None].astype(jnp.int32),
                axis=-1)[..., 0]                          # (S, K)
            # deterministic draft (q = one point mass): accept w.p. p(d)
            ok = jnp.where(temp[:, None] > 0, u < pd,
                           d == greedy[:, :K])
        else:
            ok = d == greedy[:, :K]
        valid = jj < kclip[:, None]
        accept = jnp.cumprod((ok & valid).astype(jnp.int32), axis=1)
        a = accept.sum(axis=1).astype(jnp.int32)          # (S,)
        tau_g = jnp.take_along_axis(greedy, a[:, None], axis=1)[:, 0]
        if self._sampling:
            sampled = jnp.argmax(filt + gum, axis=-1) \
                .astype(jnp.int32)                        # (S, K+1)
            # resample on rejection: p' ∝ p with the rejected draft
            # token removed (q is a point mass, so max(0, p-q)
            # renormalized is p zeroed at d)
            onehot = jax.nn.one_hot(d, V, dtype=bool)     # (S, K, V)
            resampled = jnp.argmax(
                jnp.where(onehot, -jnp.inf, filt[:, :K]) + gum[:, :K],
                axis=-1).astype(jnp.int32) if K else sampled[:, :0]
            resampled = jnp.concatenate(
                [resampled, sampled[:, K:]], axis=1)      # (S, K+1)
            rejected = a < kclip  # a < proposals => a real disagreement
            tau_s = jnp.where(rejected[:, None], resampled, sampled)
            tau_s = jnp.take_along_axis(tau_s, a[:, None], axis=1)[:, 0]
            tau = jnp.where(state["temp"]._data > 0, tau_s, tau_g) \
                .astype(jnp.int32)
        else:
            tau = tau_g
        dpad = jnp.concatenate([d, jnp.zeros((S, 1), jnp.int32)], axis=1)
        jj1 = jnp.arange(K + 1, dtype=jnp.int32)[None, :]
        tout = jnp.where(jj1 < a[:, None], dpad,
                         jnp.where(jj1 == a[:, None], tau[:, None], 0)
                         ).astype(jnp.int32)              # (S, K+1)
        counts = a + 1
        new_state = dict(state)
        new_state["tok"] = NDArray(tau[:, None], ctx=self._ctx)
        new_state["pos"] = NDArray(pos._data + counts, ctx=self._ctx)
        if self._sampling:
            new_state["rng"] = NDArray(new_keys, ctx=self._ctx)
        new_state.update(extra)
        new_state.update(dict(zip(self._pool_names, pools)))
        return ((NDArray(tout, ctx=self._ctx),
                 NDArray(counts, ctx=self._ctx))
                + tuple(new_state[k] for k in self._names))

    def _ingest_body(self, nds):
        """The ("ingest", K) executable: teacher-force up to K prefix
        tokens per slot into the paged KV cache (per-slot ragged length
        ``n``; n=0 slots are untouched — their garbage writes land on
        rows the decode loop rewrites before attending, or on the trash
        page).  Logits are discarded: ingest exists purely for its KV
        writes."""
        from .. import ndarray as F
        from ..ndarray import NDArray
        import jax.numpy as jnp

        K = self._prefix_chunk
        n_state = len(self._names)
        state = dict(zip(self._names, nds[:n_state]))
        feed, n = nds[n_state], nds[n_state + 1]
        _, extra, pools = self._chain_logits(F, state, feed._data, K)
        new_state = dict(state)
        new_state["pos"] = NDArray(
            state["pos"]._data + jnp.clip(n._data, 0, K), ctx=self._ctx)
        new_state.update(extra)
        new_state.update(dict(zip(self._pool_names, pools)))
        return tuple(new_state[k] for k in self._names)

    def _ensure_verify(self):
        if self._vrun is not None:
            return
        import jax
        import jax.numpy as jnp

        self._ensure_compiled()
        jfn = jax.jit(self._traced(self._verify_body))
        args = (self._params(),) \
            + tuple(a._data for a in self._state.values()) \
            + (jnp.zeros((self._S, self._spec_k), jnp.int32),
               jnp.zeros((self._S,), jnp.int32))
        self._vrun = self._resolve(
            jfn, args, ("verify", self._spec_k, self._ps, self._S),
            "serving_verify")

    def _ensure_ingest(self):
        if self._irun is not None:
            return
        import jax
        import jax.numpy as jnp

        self._ensure_compiled()
        jfn = jax.jit(self._traced(self._ingest_body))
        args = (self._params(),) \
            + tuple(a._data for a in self._state.values()) \
            + (jnp.zeros((self._S, self._prefix_chunk), jnp.int32),
               jnp.zeros((self._S,), jnp.int32))
        self._irun = self._resolve(
            jfn, args, ("ingest", self._prefix_chunk, self._ps, self._S),
            "serving_ingest")

    def _book_pending_compile(self):
        """Book plain-jit compiles AFTER the dispatching burst (the hot
        body never pays the analysis retrace).  Only entries whose first
        call already happened (wall_s stamped) are booked."""
        done = [s for s, r in self._pending_compile.items()
                if "wall_s" in r]
        for site in done:
            rec = self._pending_compile.pop(site)
            memwatch.note_compile(
                "ServingEngine", rec["parts"], wall_s=rec["wall_s"],
                site=site, jitted=rec["jitted"], args=rec["args"])

    # ------------------------------------------------------------------
    # the hot dispatch body (mxlint HOT_PATH_ENTRIES: no host syncs)
    # ------------------------------------------------------------------
    def _dispatch_step(self):
        """Dispatch ONE compiled decode step: device state chains to
        device state, the per-step token vector rides out as a lazy
        AsyncResult through the bounded ring.  Never blocks on device
        results (make_room bounds the window oldest-first)."""
        self._ring.make_room(self._stream_every, wait_span=False)
        arrays = [a._data for a in self._state.values()]
        t0 = time.perf_counter()
        outs = self._run(self._params(), *arrays)
        if "serving_decode" in self._pending_compile:
            self._pending_compile["serving_decode"].setdefault(
                "wall_s", time.perf_counter() - t0)
        toks = outs[0]
        from ..ndarray import NDArray

        for name, arr in zip(self._names, outs[1:]):
            self._state[name] = NDArray(arr, ctx=self._ctx)
        self._step_n += 1
        handle = AsyncResult(toks, step=self._step_n,
                             executor="ServingEngine", ring=self._ring)
        self._ring.admit(handle)
        return handle

    def _propose(self):
        """Host-side draft proposals for every live slot: (S, K) int32
        token matrix + (S,) proposal counts (ragged — 0 for empty/done
        slots and for requests the draft has nothing for)."""
        from .speculative import traced_propose

        K = self._spec_k
        draft = np.zeros((self._S, K), np.int32)
        nprop = np.zeros((self._S,), np.int32)
        for slot, meta in enumerate(self._slots):
            if meta is None or meta.done:
                continue
            toks = list(traced_propose(self._draft, meta.req,
                                       meta.req.stream.tokens, K))[:K]
            if toks:
                draft[slot, :len(toks)] = toks
                nprop[slot] = len(toks)
        return draft, nprop

    def _dispatch_spec(self):
        """Dispatch ONE compiled verify step (K draft tokens checked +
        one correction/bonus emitted per slot).  Same no-host-sync
        contract as _dispatch_step: the (S, K+1) token matrix rides out
        lazily; the per-slot counts force together with it at the
        stream boundary."""
        import jax.numpy as jnp

        draft, nprop = self._propose()
        self._last_nprop = nprop
        self._ring.make_room(self._stream_every, wait_span=False)
        arrays = [a._data for a in self._state.values()]
        t0 = time.perf_counter()
        outs = self._vrun(self._params(), *arrays, jnp.asarray(draft),
                          jnp.asarray(nprop))
        if "serving_verify" in self._pending_compile:
            self._pending_compile["serving_verify"].setdefault(
                "wall_s", time.perf_counter() - t0)
        tout, counts = outs[0], outs[1]
        from ..ndarray import NDArray

        for name, arr in zip(self._names, outs[2:]):
            self._state[name] = NDArray(arr, ctx=self._ctx)
        self._step_n += 1
        handle = AsyncResult(tout, step=self._step_n,
                             executor="ServingEngine", ring=self._ring)
        self._ring.admit(handle)
        return handle, counts

    def _consume_spec(self, handle, counts_dev):
        """Stream boundary for a verify dispatch: one (S, K+1) token
        matrix + per-slot emitted counts land together.  Row layout per
        slot: the accepted draft tokens, then the correction/bonus
        token, then padding."""
        tout = handle.asnumpy()
        counts = np.asarray(counts_dev)
        proposed = int(self._last_nprop.sum()) \
            if self._last_nprop is not None else 0
        accepted = 0
        for slot, meta in enumerate(self._slots):
            if meta is None:
                continue
            c = int(counts[slot])
            meta.pos += c  # device pos advanced by the accepted count
            if meta.done:
                continue
            req = meta.req
            accepted += max(0, c - 1)
            for i in range(c):
                tok = int(tout[slot, i])
                req.stream.append(tok)
                if req.t_first_token is None:
                    req.t_first_token = time.perf_counter()
                if tok == req.eos_id:
                    meta.done = True
                    req.stream.finish("eos")
                    break
                if len(req.stream) >= req.max_new_tokens:
                    meta.done = True
                    req.stream.finish("length")
                    break
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        # the verify boundary is per-burst, not per-request: name the
        # sampled traces that shared it so serve_report can charge the
        # rejected-draft work back to each request tree
        tids = [m.req.trace_id for m in self._slots
                if m is not None and m.req.trace_id and m.req.sampled]
        telemetry.record_spec_verify(
            proposed=proposed, accepted=accepted,
            **({"trace_ids": tids} if tids else {}))
        for slot, meta in enumerate(self._slots):
            if meta is not None and meta.done:
                self._evict(slot, meta)

    # ------------------------------------------------------------------
    # host-side scheduling (stream boundaries only)
    # ------------------------------------------------------------------
    def _pump_arrivals(self):
        while self._arrivals and self._arrivals[0][0] <= self._step_n:
            _, req = self._arrivals.pop(0)
            self.submit(req)

    def _admit_ready(self) -> int:
        free = [i for i, m in enumerate(self._slots) if m is None]
        if not free or not self._sched.depth:
            return 0
        pages_free = (self._cache.pages_free if self._cache is not None
                      else len(free))
        ready = self._sched.pop_ready(len(free), pages_free, self._ps)
        n = 0
        for i, (slot, req) in enumerate(zip(free, ready)):
            if self._admit(slot, req):
                n += 1
                continue
            # pool too tight for this request's prefix right now: it
            # went back to the queue head inside _admit; park the rest
            # behind it in order (requeue prepends, so walk backwards)
            for r in reversed(ready[i + 1:]):
                self._sched.requeue(r)
            break
        return n

    def _admit(self, slot: int, req: Request) -> bool:
        st = self._state
        if req.generation_at_admit is None:
            # cause attribution: a request admitted under generation G
            # that finishes under G' > G decoded across a weight-swap
            # window (scheduler.Request §Request tracing)
            req.generation_at_admit = self._weight_generation
        # the queue leg of the request-id span tree: queue-start ->
        # admit, recorded retroactively from the scheduler's SLO stamps
        # (t_queue_start, not t_submit: a preempted request's re-queue
        # span must not swallow its first admission's prefill+decode)
        if req.t_queue_start is not None and req.t_admit is not None \
                and telemetry.spans_enabled() \
                and (req.trace_id is None or req.sampled):
            telemetry.record_span(
                "serve_queue", req.t_queue_start, req.t_admit,
                request_id=req.id,
                **({"trace_id": req.trace_id} if req.trace_id else {}))
        if self._cache is not None:
            # pool-pressure attribution: a denied page grant for this
            # slot now names the request (and trace) it starved
            self._cache.annotate(
                slot, request_id=req.id,
                **({"trace_id": req.trace_id} if req.trace_id else {}))
        src = self._adapter.prefill_src(req)
        if src is not None:
            self._prefill_into(slot, req, src)
        st["tok"][slot, 0] = req.bos_id
        st["pos"][slot] = 0
        if self._sampling:
            self._install_sampling(slot, req)
        self._adapter.install(st, slot, req)
        self._admit_seq += 1
        meta = _Active(req, self._admit_seq)
        self._slots[slot] = meta
        if req.prefix.size:
            if not self._install_prefix(slot, meta, req):
                self._rollback_admit(slot, req)
                return False
        return True

    def _prefill_into(self, slot: int, req: Request, src) -> None:
        """Run (or reuse) the prefill executable for one admission.
        With the prefix cache on, identical prefill inputs hit a cached
        device copy of the output rows — the 'prefill once' half of
        prefix reuse (the encoder memory for a repeated source)."""
        st = self._state
        names = list(self._adapter.prefill_names)
        pkey = (prefix_key("prefill", src)
                if self._prefix is not None else None)
        if pkey is not None:
            e = self._prefix.get(pkey, self._weight_generation)
            if e is not None:
                for name in names:
                    st[name][slot] = e["payload"]["rows"][name]
                req.prefill_ms = 0.0
                if req.prefix_hit is None:
                    req.prefix_hit = True
                telemetry.record_serve_prefix(
                    kind="prefill", hit=True, tokens=int(req.tokens.size),
                    request_id=req.id,
                    **({"trace_id": req.trace_id} if req.trace_id else {}))
                return
        self._ensure_prefill(src)
        import jax.numpy as jnp

        t0 = time.perf_counter()
        outs = self._prefill_run(self._params(), jnp.asarray(src))
        t1 = time.perf_counter()
        # prefill_ms is DISPATCH wall (async queueing, like step
        # events — see telemetry.record_step's contract)
        req.prefill_ms = round((t1 - t0) * 1e3, 3)
        if telemetry.spans_enabled() \
                and (req.trace_id is None or req.sampled):
            telemetry.record_span(
                "serve_prefill", t0, t1, request_id=req.id,
                **({"trace_id": req.trace_id} if req.trace_id else {}))
        if "serving_prefill" in self._pending_compile:
            self._pending_compile["serving_prefill"].setdefault(
                "wall_s", time.perf_counter() - t0)
            self._book_pending_compile()
        from ..ndarray import NDArray

        rows = {}
        for name, arr in zip(self._prefill_names, outs):
            row = NDArray(arr, ctx=self._ctx)[0]
            st[name][slot] = row
            rows[name] = row
        if pkey is not None:
            for d in self._prefix.put(pkey, "prefill",
                                      self._weight_generation,
                                      {"rows": rows, "owner": None}):
                self._release_prefix_entry(d)
            req.prefix_hit = False
            telemetry.record_serve_prefix(
                kind="prefill", hit=False, tokens=int(req.tokens.size),
                request_id=req.id,
                **({"trace_id": req.trace_id} if req.trace_id else {}))

    def _install_sampling(self, slot: int, req: Request) -> None:
        """Per-slot sampling state at admission.  The RNG key is a pure
        function of the request's seed — decoding is reproducible across
        restarts, slot assignments and recompute-preemptions (the key
        re-derives identically on re-admission)."""
        import jax

        st = self._state
        st["temp"][slot] = req.temperature
        st["topk"][slot] = req.top_k
        st["topp"][slot] = req.top_p
        if req.seed is None:
            # stamped on the request so a preemption re-derives the
            # same stream (deterministic re-decode, like greedy)
            req.seed = int.from_bytes(os.urandom(4), "little")
        st["rng"][slot] = np.asarray(jax.random.PRNGKey(req.seed))

    # ------------------------------------------------------------------
    # prefix cache: COW page forks + teacher-forced ingest
    # ------------------------------------------------------------------
    def _install_prefix(self, slot: int, meta: _Active,
                        req: Request) -> bool:
        """Put the request's forced decoder prefix into the slot's KV
        pages: fork a cached entry's pages (hit) or teacher-force the
        tokens through the ("ingest", K) executable and register the
        result (miss).  Returns False when the pool cannot hold the
        prefix even after dropping cache entries — the caller rolls the
        admission back."""
        T = int(req.prefix.size)
        key = (prefix_key(req.tokens, req.bos_id, req.prefix)
               if self._prefix is not None else None)
        if key is not None:
            e = self._prefix.get(key, self._weight_generation)
            if e is not None and self._fork_from_entry(slot, e, req):
                meta.pos = T
                if req.prefix_hit is None:
                    req.prefix_hit = True
                telemetry.record_serve_prefix(
                    kind="pages", hit=True, tokens=T, request_id=req.id,
                    **({"trace_id": req.trace_id} if req.trace_id else {}))
                return True
        need = pages_for(T, self._ps) - len(self._cache.owned(slot))
        if not self._alloc_prefix_pages(slot, need):
            return False
        self._state["table"][slot] = self._cache.table_row(slot, self._P)
        self._ingest_prefix(slot, req)
        meta.pos = T
        if key is not None:
            self._register_prefix(slot, key, T)
            req.prefix_hit = False
            telemetry.record_serve_prefix(
                kind="pages", hit=False, tokens=T, request_id=req.id,
                **({"trace_id": req.trace_id} if req.trace_id else {}))
        return True

    def _fork_from_entry(self, slot: int, e: dict, req: Request) -> bool:
        """Copy-on-write fork: adopt the entry's FULL pages (shared,
        refcounted — never written again: the slot's first write lands
        at pos >= prefix_len) and device-copy the partial tail page into
        a private page the slot may keep writing.  Bitwise-identical
        continuation: the forked slot decodes over the exact pool rows
        the cold ingest produced."""
        st = self._state
        T = int(e["payload"]["len"])
        pages = e["payload"]["pages"]
        full, tail = T // self._ps, T % self._ps
        if full:
            self._cache.adopt(slot, pages[:full])
        if tail:
            got = self._cache.alloc(slot, 1)
            if got is None and self._drop_one_prefix_entry():
                got = self._cache.alloc(slot, 1)
            if not got:
                self._cache.free_slot(slot)  # release the adoption
                st["table"][slot] = 0
                return False
            for name in self._pool_names:
                st[name][got[0]] = st[name][pages[full]]
        st["table"][slot] = self._cache.table_row(slot, self._P)
        st["pos"][slot] = T
        st["tok"][slot, 0] = int(req.prefix[-1])
        return True

    def _register_prefix(self, slot: int, key: str, T: int) -> None:
        """After a cold ingest: share the slot's full prefix pages into
        a cache entry and give the entry a private COPY of the partial
        tail page (the donor keeps writing its own tail at pos >= T —
        the entry's copy must stay frozen)."""
        full, tail = T // self._ps, T % self._ps
        self._admit_seq += 1  # unique owner key per registration
        ek = f"prefix:{key[:16]}:{self._admit_seq}"
        slot_pages = self._cache.owned(slot)
        entry_pages = list(slot_pages[:full])
        if full:
            self._cache.adopt(ek, entry_pages)
        if tail:
            got = self._cache.alloc(ek, 1)
            if got is None:
                # no room for the tail copy: don't register a partial
                # entry (a fork would miss the tail rows)
                self._cache.free_slot(ek)
                return
            st = self._state
            for name in self._pool_names:
                st[name][got[0]] = st[name][slot_pages[full]]
            entry_pages.append(got[0])
        for d in self._prefix.put(key, "pages", self._weight_generation,
                                  {"owner": ek, "pages": entry_pages,
                                   "len": T}):
            self._release_prefix_entry(d)

    def _ingest_prefix(self, slot: int, req: Request) -> None:
        """Teacher-force [bos, p_1..p_{T-1}] into the slot's KV pages in
        ("ingest", K)-sized chunks; afterwards the slot sits at pos=T
        with tok=p_T — exactly the state T forced greedy decode steps
        would have produced, so the continuation is bitwise identical
        to decoding the prefix the slow way."""
        import jax.numpy as jnp
        from ..ndarray import NDArray

        self._ensure_ingest()
        T = int(req.prefix.size)
        feed_seq = np.concatenate(
            [[req.bos_id], req.prefix[:-1]]).astype(np.int32)
        Kc = self._prefix_chunk
        t0 = time.perf_counter()
        done = 0
        while done < T:
            n = min(Kc, T - done)
            feed = np.zeros((self._S, Kc), np.int32)
            feed[slot, :n] = feed_seq[done:done + n]
            nvec = np.zeros((self._S,), np.int32)
            nvec[slot] = n
            arrays = [a._data for a in self._state.values()]
            outs = self._irun(self._params(), *arrays,
                              jnp.asarray(feed), jnp.asarray(nvec))
            if "serving_ingest" in self._pending_compile:
                self._pending_compile["serving_ingest"].setdefault(
                    "wall_s", time.perf_counter() - t0)
                self._book_pending_compile()
            for name, arr in zip(self._names, outs):
                self._state[name] = NDArray(arr, ctx=self._ctx)
            done += n
        self._state["tok"][slot, 0] = int(req.prefix[-1])
        if telemetry.spans_enabled() \
                and (req.trace_id is None or req.sampled):
            telemetry.record_span(
                "serve_ingest", t0, time.perf_counter(),
                request_id=req.id, tokens=T,
                **({"trace_id": req.trace_id} if req.trace_id else {}))

    def _alloc_prefix_pages(self, slot: int, n: int) -> bool:
        """Allocate ``n`` pages for a prefix, dropping LRU cache entries
        under pool pressure (evict-before-preempt: cached prefixes are
        recomputable, live requests cost a full re-decode)."""
        if n <= 0:
            return True
        while self._cache.alloc(slot, n) is None:
            if not self._drop_one_prefix_entry():
                return False
        return True

    def _drop_one_prefix_entry(self) -> bool:
        if self._prefix is None:
            return False
        e = self._prefix.pop_lru("pages")
        if e is None:
            return False
        self._release_prefix_entry(e)
        telemetry.record("serve_prefix_evict", executor="ServingEngine",
                         key=e["key"][:12], tokens=e["payload"]["len"])
        return True

    def _release_prefix_entry(self, e: dict) -> None:
        owner = e["payload"].get("owner")
        if owner is not None and self._cache is not None:
            self._cache.free_slot(owner)

    def _rollback_admit(self, slot: int, req: Request) -> None:
        """Undo a partially-completed admission (prefix didn't fit):
        the slot reads empty again and the request parks at the queue
        head, exactly like a preemption before any decode."""
        st = self._state
        if self._cache is not None:
            self._cache.free_slot(slot)
        st["table"][slot] = 0
        st["pos"][slot] = 0
        st["tok"][slot] = 0
        for name in self._extra_names:
            st[name][slot] = 0
        for name in self._samp_names:
            st[name][slot] = 0
        self._slots[slot] = None
        req.t_admit = None
        req.prefill_ms = 0.0
        self._sched.requeue(req)

    def _ensure_pages(self, burst: int) -> int:
        """Grow page tables so every active, unfinished slot can decode
        ``burst`` more positions; shrinks the burst when the pool runs
        dry.  Under real pool pressure (some slot cannot advance even
        one step) the YOUNGEST-admitted request is preempted back to the
        queue head (vLLM-style recompute preemption — greedy decode is
        deterministic, so re-decoding reproduces its tokens) until the
        survivors can advance; a single request that cannot fit at all
        is a configuration error and raises."""
        if self._cache is None:
            return burst
        while True:
            feas = self._grow_tables(burst)
            if feas > 0:
                return feas
            # evict-before-preempt: cached prefixes are cheap to rebuild
            # (one ingest), a live request costs a full re-decode
            if self._drop_one_prefix_entry():
                continue
            cands = [(m.seq, slot, m) for slot, m in enumerate(self._slots)
                     if m is not None and not m.done]
            if len(cands) <= 1:
                raise MXNetError(
                    "paged KV pool cannot hold even one in-flight "
                    "request — raise MX_SERVE_POOL_PAGES (or lower "
                    f"max_len); pool {self._cache.num_pages} pages of "
                    f"{self._ps} tokens")
            _, slot, meta = max(cands)
            self._preempt(slot, meta)

    def _grow_tables(self, burst: int) -> int:
        """One growth pass; returns the feasible burst (0 = some slot is
        starved)."""
        feas = burst
        st = self._state
        for slot, meta in enumerate(self._slots):
            if meta is None or meta.done:
                continue
            rem = meta.req.max_new_tokens - len(meta.req.stream)
            want = min(burst, rem)
            need_pages = pages_for(meta.pos + want, self._ps)
            have = len(self._cache.owned(slot))
            if need_pages > have:
                if self._cache.alloc(slot, need_pages - have) is None:
                    # pool can't cover the whole growth: grab what's left
                    while (self._cache.pages_free
                           and len(self._cache.owned(slot)) < need_pages):
                        self._cache.alloc(slot, 1)
                st["table"][slot] = self._cache.table_row(slot, self._P)
            cap = self._cache.capacity_rows(slot)
            if cap - meta.pos < want:
                feas = min(feas, cap - meta.pos)
        return max(0, feas)

    def _preempt(self, slot: int, meta: _Active):
        """Evict a request mid-decode under pool pressure: pages free
        NOW, the request returns to the queue HEAD and recomputes from
        scratch on re-admission (its stream resets — deterministic
        greedy decode re-emits identical tokens)."""
        st = self._state
        self._cache.free_slot(slot)
        st["table"][slot] = 0
        st["pos"][slot] = 0
        for name in self._extra_names:
            st[name][slot] = 0
        for name in self._samp_names:
            st[name][slot] = 0
        req = meta.req
        req.stream.tokens.clear()
        req.t_admit = None
        req.t_first_token = None  # TTFT re-stamps after re-admission,
        #                           still measured from the ORIGINAL submit
        req.prefill_ms = 0.0
        req.preemptions += 1
        telemetry.record("serve_preempt", request_id=req.id,
                         decoded=meta.pos,
                         **({"trace_id": req.trace_id}
                            if req.trace_id else {}))
        self._sched.requeue(req)
        self._slots[slot] = None

    def _consume(self, handles):
        """Stream boundary: force the burst's token handles (the ONLY
        host readback), append to per-request streams, finish + evict
        completed requests so their pages free immediately."""
        for h in handles:
            toks = h.asnumpy()
            for slot, meta in enumerate(self._slots):
                if meta is None:
                    continue
                meta.pos += 1  # device pos advanced for every slot
                if meta.done:
                    continue
                req = meta.req
                tok = int(toks[slot])
                req.stream.append(tok)
                if req.t_first_token is None:
                    # stream-boundary resolution: the whole burst's tokens
                    # land together, so TTFT is stamped when the FIRST
                    # one becomes host-visible — the user-visible moment
                    req.t_first_token = time.perf_counter()
                if tok == req.eos_id:
                    meta.done = True
                    req.stream.finish("eos")
                elif len(req.stream) >= req.max_new_tokens:
                    meta.done = True
                    req.stream.finish("length")
        for slot, meta in enumerate(self._slots):
            if meta is not None and meta.done:
                self._evict(slot, meta)

    def _evict(self, slot: int, meta: _Active):
        st = self._state
        if self._cache is not None:
            self._cache.free_slot(slot)
        st["table"][slot] = 0
        st["pos"][slot] = 0
        for name in self._extra_names:
            st[name][slot] = 0
        for name in self._samp_names:
            st[name][slot] = 0
        req = meta.req
        now = time.perf_counter()
        decode_ms = max(0.0, (now - req.t_admit) * 1e3
                        - req.prefill_ms) if req.t_admit else 0.0
        # total_ms is the TRUE submit->finish wall: for a preempted
        # request the per-leg fields cover only the last admission, but
        # the SLO latency must include the discarded service period
        total_ms = ((now - req.t_submit) * 1e3
                    if req.t_submit is not None else None)
        # per-request cause attribution from the breadcrumbs stamped as
        # the request moved through the engine, in priority order: a
        # recompute-preemption dominates (it rewinds the whole stream),
        # then a weight-swap window crossing, then a prefix-cache miss
        # (a request with no prefix candidate attributes to "none")
        if req.preemptions:
            cause = "preempt"
        elif (req.generation_at_admit is not None
              and req.generation_at_admit != self._weight_generation):
            cause = "swap"
        elif req.prefix_hit is False:
            cause = "cache_miss"
        else:
            cause = "none"
        telemetry.record_serve_request(
            queue_wait_ms=req.queue_wait_ms, prefill_ms=req.prefill_ms,
            decode_ms=round(decode_ms, 3), tokens=len(req.stream),
            ttft_ms=round(req.ttft_ms, 3),
            total_ms=round(total_ms, 3) if total_ms is not None else None,
            request_id=req.id, reason=req.stream.finish_reason,
            precision=self._precision, cause=cause,
            preemptions=req.preemptions,
            **({"trace_id": req.trace_id, "sampled": req.sampled}
               if req.trace_id else {}))
        self._slots[slot] = None

    # ------------------------------------------------------------------
    # introspection + batched beam serving
    # ------------------------------------------------------------------
    def statusz_snapshot(self) -> dict:
        """Jax-free engine status for the serving front door's /statusz
        (plain attribute reads — safe from the replica's HTTP handler
        threads while the run loop decodes)."""
        snap = {
            "slots": self._S,
            "active_slots": sum(1 for m in self._slots if m is not None),
            "queue_depth": self._sched.depth,
            "queue_bound": self._sched.bound,
            "steps": self._step_n,
            "weight_generation": self._weight_generation,
            "precision": self._precision,
            "sampling": bool(self._sampling),
            "spec_k": self._spec_k,
            "max_len": self._max_len,
        }
        if self._cache is not None:
            snap["pages_free"] = self._cache.pages_free
            snap["pages_total"] = self._cache.num_pages
        if self._prefix is not None:
            snap["prefix_entries"] = len(self._prefix)
            snap["prefix_hits"] = self._prefix.hits
            snap["prefix_misses"] = self._prefix.misses
        if self._spec_k:
            snap["spec_proposed"] = self._spec_proposed
            snap["spec_accepted"] = self._spec_accepted
        return snap

    def serve_beam(self, requests, beam_size: int = 4, alpha: float = 0.6,
                   sync_every: int = 8) -> Dict[str, np.ndarray]:
        """Batched beam serving: decode ``requests`` with the model's
        device-resident beam search (``translate`` — beam bookkeeping
        stays on device, host syncs every ``sync_every`` steps) in ONE
        batch per (bos, eos) group, and return {id: tokens} trimmed the
        same way the greedy engine streams them (bos dropped, cut just
        after eos).  Quality-first counterpart to :meth:`serve`: no
        continuous batching or mid-flight joins, but each request gets a
        beam_size-wide search instead of a single greedy/sampled lane."""
        model = getattr(self._adapter, "model", None)
        if model is None or not hasattr(model, "translate"):
            raise MXNetError(
                "serve_beam needs an adapter exposing .model with "
                "translate() (the seq2seq TransformerAdapter)")
        from ..ndarray import array as nd_array

        requests = list(requests)
        groups: Dict[tuple, List[Request]] = {}
        for req in requests:
            if req.temperature > 0 or req.prefix.size:
                raise MXNetError(
                    f"request {req.id}: beam serving is search, not "
                    "sampling — temperature/prefix don't apply")
            groups.setdefault((req.bos_id, req.eos_id), []).append(req)
        out: Dict[str, np.ndarray] = {}
        for (bos, eos), grp in groups.items():
            t0 = time.perf_counter()
            src_w = max(int(r.tokens.size) for r in grp)
            src = np.zeros((len(grp), src_w), np.int32)
            for i, r in enumerate(grp):
                src[i, :r.tokens.size] = r.tokens
            max_new = max(r.max_new_tokens for r in grp)
            hyp = model.translate(
                nd_array(src, ctx=self._ctx, dtype="int32"), bos_id=bos,
                eos_id=eos, max_len=max_new + 1, beam_size=beam_size,
                alpha=alpha, sync_every=sync_every,
                page_size=self._ps if self._cache is not None else None)
            t1 = time.perf_counter()
            for i, r in enumerate(grp):
                toks = list(hyp[i, 1:])  # row 0 is bos
                if eos in toks:
                    toks = toks[:toks.index(eos) + 1]
                toks = toks[:r.max_new_tokens]
                for t in toks:
                    r.stream.append(t)
                r.stream.finish("eos" if (toks and toks[-1] == eos)
                                else "length")
                out[r.id] = r.stream.asarray()
                telemetry.record_serve_request(
                    queue_wait_ms=0.0, prefill_ms=0.0,
                    decode_ms=round((t1 - t0) * 1e3, 3),
                    tokens=len(toks),
                    ttft_ms=round((t1 - t0) * 1e3, 3),
                    total_ms=round((t1 - t0) * 1e3, 3),
                    request_id=r.id, reason=r.stream.finish_reason,
                    precision=self._precision, beam=beam_size)
        return out
