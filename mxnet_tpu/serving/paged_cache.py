"""Paged KV cache: ragged decode lengths sharing one preallocated pool
(docs/SERVING.md §Paged KV cache).

The design of *Ragged Paged Attention* (PAPERS.md, arxiv 2604.15464):
instead of one dense ``(B, Lmax, C)`` K/V buffer per layer — whose batch
rows must all be the same padded length, and whose shape retraces the
decode executable whenever the padded length changes — each layer keeps a
fixed pool of ``(num_pages, page_size, heads, head_dim)`` blocks plus a
per-slot **page table**.  A request of any length owns just the pages its
tokens fill; attention gathers the slot's pages back into a dense view by
table lookup, so the compiled decode step sees ONE static shape
regardless of how long each in-flight request has grown.  Freed pages
return to the pool the moment a request finishes (continuous batching's
memory half).

Two layers live here:

  * functional math (``page_coords`` / ``write_page`` / ``gather_pages``
    / ``paged_attend``) — pure NDArray-in/NDArray-out helpers that run
    eagerly AND inside a jit trace (the serving engine's compiled decode
    step, ``models.transformer.translate``'s device-side beam loop).
    ``paged_attend`` reuses the exact ``_attend_cached`` op sequence on
    the gathered dense view, so paged decode is **bitwise identical** to
    the dense-cache decode for the same tokens (asserted by
    tests/test_serving.py).
  * ``PagedKVCache`` — the host-side allocator (free list + per-owner
    page ownership + per-page REFCOUNTS) and pool factory.  Page 0 is
    reserved as the trash page: empty slots' all-zero table rows route
    their (discarded) writes there, so inactive decode lanes can never
    corrupt a live request's cache.

Owners are opaque hashable keys: decode slots (ints) and prefix-cache
entries (strings) share one pool.  ``adopt`` lets a second owner share a
page another owner already holds (copy-on-write prefix reuse — the
serving engine's prefix cache, docs/SERVING.md §Prefix cache): the page
returns to the free list only when its LAST owner releases it.  A shared
page must never be written through — the engine guarantees this by
sharing only FULL pages (a forked request's first write lands at
``pos >= prefix_len``, inside a private page), and by giving the cache
entry its own COPY of any partially-filled tail page.

The fused alternative to the gather (``ops.pallas.paged_attention``)
never materialises the dense view; see ``PagedStepCache(fused=True)``.
"""
from __future__ import annotations

import math
from typing import List, Optional

from ..base import MXNetError

__all__ = ["PagedKVCache", "PagedStepCache", "page_coords", "write_page",
           "gather_pages", "paged_attend", "pages_for"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _nd(data, like):
    from ..ndarray import NDArray

    return NDArray(data, ctx=like.context)


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``n_tokens`` cache rows."""
    return max(0, math.ceil(n_tokens / page_size))


# ---------------------------------------------------------------------------
# functional math (eager + trace)
# ---------------------------------------------------------------------------
def page_coords(table, pos, page_size: int):
    """Device coordinates of decode position ``pos`` for every slot.

    table: (S, P) int32 page table; pos: (S,) int32 per-slot position
    (or (1,) broadcasting a uniform position, the translate case).
    Returns ``(pages, rows)`` int32 NDArrays — ``pool[pages[s], rows[s]]``
    is where slot ``s`` writes this step's k/v.  Out-of-range positions
    clamp into the table (jnp gather semantics); callers keep positions
    in range via the allocator."""
    jnp = _jnp()
    t, p = table._data, pos._data
    if p.shape[0] != t.shape[0]:
        p = jnp.broadcast_to(p, (t.shape[0],))
    col = (p // page_size).astype(jnp.int32)
    pages = jnp.take_along_axis(t, col[:, None], axis=1)[:, 0]
    rows = (p % page_size).astype(jnp.int32)
    return _nd(pages, table), _nd(rows, table)


def write_page(pool, pages, rows, vals):
    """Scatter one token's k (or v) per slot into the pool.

    pool: (N, page_size, H, hd); pages/rows: (S,) int32; vals: (S, H, hd).
    Returns the updated pool (functional — jax arrays are immutable)."""
    new = pool._data.at[pages._data, rows._data].set(vals._data)
    return _nd(new, pool)


def gather_pages(pool, table):
    """Dense (S, P*page_size, H*hd) view of every slot's pages.

    The gather-by-page-table that makes ragged slots look like one
    fixed-shape dense cache to the attention math.  Rows beyond a slot's
    real length hold stale/zero garbage — callers mask them via ``keep``
    exactly as the dense cache masks its unwritten tail."""
    jnp = _jnp()
    S, P = table.shape
    N, ps, H, hd = pool.shape
    flat = jnp.take(pool._data, table._data.reshape(-1), axis=0)
    return _nd(flat.reshape(S, P * ps, H * hd), pool)


def paged_attend(F, q_t, k_pool, v_pool, table, keep, num_heads, head_dim):
    """One-query attention over paged K/V: gather the slots' pages into
    the dense layout, then run the EXACT ``_attend_cached`` op sequence
    on it.  Same values through the same eager executables => bitwise
    identical to the dense-cache decode (the parity contract)."""
    from ..models.transformer import _attend_cached

    K = gather_pages(k_pool, table)
    V = gather_pages(v_pool, table)
    return _attend_cached(F, q_t, K, V, keep, num_heads, head_dim)


class PagedStepCache:
    """One decode step's view of a single layer's paged K/V pools — the
    cache object ``TransformerDecoderCell.step`` writes/attends through
    (the paged twin of ``models.transformer.DenseStepCache``).

    ``pages``/``rows`` (from :func:`page_coords`) are computed once per
    step by the caller and shared across layers; ``keep`` is the
    (S, P*page_size) validity mask (1.0 = attend).  After
    ``update_and_attend`` the updated pools are on ``.k_pool``/
    ``.v_pool`` for the caller to thread into the next step's state.

    ``fused=True`` routes attention through the Pallas paged decode
    kernel (ops/pallas/paged_attention) instead of gather+dense — the
    on-chip path that never materialises the dense view; numerically
    equivalent (online softmax) but not bitwise, so it is opt-in
    (``lengths`` (S,) int32 is required: the kernel masks by length, not
    by ``keep``)."""

    def __init__(self, k_pool, v_pool, table, pages, rows, keep,
                 lengths=None, fused: bool = False):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.table = table
        self.pages = pages
        self.rows = rows
        self.keep = keep
        self.lengths = lengths
        self._fused = fused
        if fused and lengths is None:
            raise MXNetError("PagedStepCache(fused=True) needs per-slot "
                             "lengths for the kernel's ragged masking")

    def update_and_attend(self, F, attn, q_t, k_t, v_t):
        H, hd = attn._num_heads, attn._head_dim
        S = k_t.shape[0]
        k_vals = k_t.reshape(S, H, hd)
        v_vals = v_t.reshape(S, H, hd)
        self.k_pool = write_page(self.k_pool, self.pages, self.rows, k_vals)
        self.v_pool = write_page(self.v_pool, self.pages, self.rows, v_vals)
        if self._fused:
            from ..ops.pallas.paged_attention import paged_decode_attention

            q = q_t.reshape(S, H, hd)
            out = paged_decode_attention(
                q._data, self.k_pool._data, self.v_pool._data,
                self.table._data, self.lengths._data)
            return _nd(out.reshape(S, 1, H * hd), q_t)
        return paged_attend(F, q_t, self.k_pool, self.v_pool, self.table,
                            self.keep, H, hd)


# ---------------------------------------------------------------------------
# pool + allocator
# ---------------------------------------------------------------------------
class PagedKVCache:
    """Fixed pool of KV pages per decoder layer + the host-side page
    allocator.

    The pools are plain NDArrays handed to the caller (the serving
    engine threads them through its compiled decode step as functional
    state; ``translate`` updates them in its beam loop) — this object
    owns only the *bookkeeping*: which pages are free, which slot owns
    which pages.  Page 0 is reserved (the trash page inactive slots
    write to), so ``num_pages`` must leave room for it."""

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_heads: int, head_dim: int, ctx=None,
                 dtype: str = "float32"):
        if num_pages < 2:
            raise MXNetError("PagedKVCache needs >= 2 pages (page 0 is "
                             "the reserved trash page)")
        from ..context import current_context
        from ..ndarray import zeros as nd_zeros

        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.ctx = ctx if ctx is not None else current_context()
        shape = (self.num_pages, self.page_size, self.num_heads,
                 self.head_dim)
        self.pools = [(nd_zeros(shape, ctx=self.ctx, dtype=dtype),
                       nd_zeros(shape, ctx=self.ctx, dtype=dtype))
                      for _ in range(self.num_layers)]
        # LIFO free list: recently-freed (cache-warm) pages reused first
        self._free: List[int] = list(range(1, self.num_pages))
        self._owned: dict = {}
        self._refs: dict = {}  # page -> owner count (COW sharing)
        self._notes: dict = {}  # owner -> observability metadata

    @property
    def pages_free(self) -> int:
        return len(self._free)

    def owned(self, slot) -> List[int]:
        return list(self._owned.get(slot, ()))

    def refcount(self, page: int) -> int:
        """How many owners hold ``page`` (0 = free/never granted)."""
        return self._refs.get(int(page), 0)

    def annotate(self, owner, **attrs) -> None:
        """Attach observability metadata to ``owner`` (the serving
        engine stamps request_id/trace_id at admission) so pool-pressure
        events name the request whose growth was denied, not just a
        slot index.  Cleared when the owner releases its pages."""
        if attrs:
            self._notes.setdefault(owner, {}).update(attrs)

    def annotation(self, owner) -> dict:
        """The metadata :meth:`annotate` attached (empty dict if none)."""
        return dict(self._notes.get(owner, ()))

    def alloc(self, slot, n_pages: int) -> Optional[List[int]]:
        """Grant ``n_pages`` more pages to ``slot`` (all-or-nothing).
        Returns the newly granted pages, or None when the pool cannot
        cover the request — the caller shrinks its dispatch burst or
        defers the admission (never partial: a half-grown table would
        let a decode position land on the trash page)."""
        n_pages = int(n_pages)
        if n_pages <= 0:
            return []
        if n_pages > len(self._free):
            # pool pressure, attributed: the denial that triggers burst
            # shrink / preemption upstream names the starved request via
            # its annotation (at most one event per owner per growth
            # pass — the engine never retries a denied all-or-nothing
            # grant within a pass)
            from .. import telemetry

            telemetry.record("serve_pool_pressure", want=n_pages,
                             free=len(self._free),
                             **self._notes.get(slot, {}))
            return None
        got = [self._free.pop() for _ in range(n_pages)]
        self._owned.setdefault(slot, []).extend(got)
        for p in got:
            self._refs[p] = 1
        return got

    def adopt(self, owner, pages) -> None:
        """Add ``owner`` as a co-owner of already-granted ``pages``
        (copy-on-write sharing: a prefix-cache hit forks a page table by
        adopting the entry's full pages instead of re-prefilling them).
        Each page's refcount bumps by one; it returns to the free list
        only when the last owner releases it.  Adopting a page nobody
        owns is a bookkeeping bug and raises."""
        pages = [int(p) for p in pages]
        for p in pages:
            if self._refs.get(p, 0) <= 0:
                raise MXNetError(
                    f"adopt: page {p} is not currently owned — a free "
                    "page cannot be shared (allocator bookkeeping bug)")
        self._owned.setdefault(owner, []).extend(pages)
        for p in pages:
            self._refs[p] += 1

    def free_slot(self, slot) -> int:
        """Release every page ``slot`` owns (request finished / evicted /
        prefix-cache entry dropped).  Pages whose refcount hits zero
        return to the pool — shared (adopted) pages survive until their
        last owner lets go.  Returns how many pages actually came back
        to the free list."""
        pages = self._owned.pop(slot, [])
        self._notes.pop(slot, None)
        freed = 0
        for p in pages:
            left = self._refs.get(p, 1) - 1
            if left <= 0:
                self._refs.pop(p, None)
                self._free.append(p)
                freed += 1
            else:
                self._refs[p] = left
        return freed

    def capacity_rows(self, slot) -> int:
        """How many cache rows the slot's granted pages can hold."""
        return len(self._owned.get(slot, ())) * self.page_size

    def table_row(self, slot, max_pages: int):
        """The slot's page-table row, zero-padded to ``max_pages``
        (numpy int32 — callers setitem it into the device table)."""
        import numpy as np

        pages = self._owned.get(slot, [])
        if len(pages) > max_pages:
            raise MXNetError(f"slot {slot} owns {len(pages)} pages > "
                             f"table width {max_pages}")
        row = np.zeros((max_pages,), np.int32)
        row[:len(pages)] = pages
        return row
