"""Serving front door: per-replica HTTP servers + a multi-replica
router (docs/SERVING.md §Front door).

Two stdlib-only pieces (``http.server`` + daemon threads, the
metrics_server pattern — no framework, nothing to install):

  * :class:`ReplicaServer` fronts ONE :class:`~.engine.ServingEngine`:
    it owns a dedicated engine-driver thread (the ONLY thread that ever
    touches jax — HTTP handlers just build :class:`~.scheduler.Request`
    objects, submit, and poll the request's ``TokenStream.finished``
    flag, so the handler code is jax-free by construction and mxlint's
    reachability check keeps it that way).  It advertises itself by
    writing ``serve-port-<rank>.json`` next to the metrics portfiles
    (atomic tmp+rename; tools/launch.py cleans them up the same way).

  * :class:`Router` is the client-facing load balancer: it discovers
    replicas from those portfiles, health-polls their ``/healthz``,
    dispatches each ``/generate`` to the healthy replica with the
    fewest outstanding requests, and pins ``session`` ids to a replica
    (affinity keeps a conversation's prefix-cache pages hot on one
    engine — the COW prefix cache is per-replica).  A replica that
    drops mid-request is marked dead and the request FAILS OVER to the
    next healthy replica (decoding restarts — greedy/seeded decode is
    deterministic, so the client sees identical tokens, just later).
    ``/admin/drain`` takes a replica out of rotation gracefully
    (in-flight requests finish; health polling re-adds it after
    ``/admin/undrain``) — composing with ``--elastic`` rescale and
    weight hot-swap: drain, swap/restart, undrain, no dropped requests.

Routes (replica): ``POST /generate``, ``GET /statusz``, ``GET
/healthz``, ``POST /admin/drain``, ``POST /admin/undrain``.
Routes (router): the same, plus drain/undrain take ``?rank=N``.

``/generate`` body (JSON): ``prompt`` (list of token ids, required),
``max_new_tokens``, ``bos_id``/``eos_id`` (default to the replica's
configured pair), ``temperature``/``top_k``/``top_p``/``seed``
(defaults from ``MX_SERVE_TEMPERATURE`` / ``MX_SERVE_TOP_K`` /
``MX_SERVE_TOP_P`` — applied at this HTTP layer, never inside the
engine), ``prefix`` (forced decoder prefix; prefix-cache candidate),
``session`` (router affinity key), ``timeout_s``.  Response:
``{"request_id", "tokens", "finish_reason", "replica", ...}``.

**Request tracing** (docs/OBSERVABILITY.md §Request tracing): the Router
mints a trace context per /generate — ``trace_id`` (16 hex chars), the
id of its open ``serve_route`` span, and a head-sampling bit
(``MX_RQTRACE_SAMPLE``, default 1.0) — and propagates it to the replica
in the ``X-MX-Trace`` header (``<trace_id>;parent=<span>;sampled=<0|1>``).
The replica threads it into the :class:`~.scheduler.Request` so every
engine span/event carries the trace id; the router wraps the whole
dispatch residence in a paired ``serve_route`` span and each attempt in
a ``serve_dispatch`` span (a failover is ONE trace with TWO dispatch
spans).  Unsampled requests skip span emission on the hot path but the
router still measures them — on an error or TTFT SLO breach the spans
are recorded retroactively (``late_sampled``), so the tail is never
lost.  ``GET /tracez`` shows the last K completed request trees
(``MX_RQTRACE_TRACEZ_K``) and every in-flight request with its open
span.  ``MX_RQTRACE=0`` switches the whole subsystem off.
"""
from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .. import telemetry
from ..base import MXNetError
from .scheduler import Request

__all__ = ["ReplicaServer", "Router", "serve_portfile_path",
           "discover_replicas", "TRACE_HEADER", "rqtrace_enabled",
           "mint_trace", "format_trace_header", "parse_trace_header"]

_LOG = logging.getLogger("mxnet_tpu.serving.router")


def serve_portfile_path(directory: str, rank_id: int) -> str:
    """Per-replica portfile path (mirrored in tools/launch.py, which
    must stay importable without jax/mxnet_tpu — keep in sync)."""
    return os.path.join(directory, f"serve-port-{rank_id}.json")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def discover_replicas(directory: str) -> List[dict]:
    """Parse every ``serve-port-*.json`` in ``directory`` (torn/garbage
    files are skipped — the atomic rename means they're transient)."""
    out = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("serve-port-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                p = json.load(f)
            out.append({"rank": int(p["rank"]), "host": str(p["host"]),
                        "port": int(p["port"])})
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


# ---------------------------------------------------------------------------
# trace context (docs/OBSERVABILITY.md §Request tracing)
# ---------------------------------------------------------------------------
TRACE_HEADER = "X-MX-Trace"


def rqtrace_enabled() -> bool:
    """Request tracing rides the front door by default; ``MX_RQTRACE=0``
    is the kill switch (spans, /tracez bookkeeping and header
    propagation all stop — the bench lever for the <2% overhead gate)."""
    return os.environ.get("MX_RQTRACE", "1").lower() not in (
        "0", "false", "off")


def mint_trace(sample: Optional[float] = None) -> Optional[dict]:
    """A fresh trace context ``{"trace_id", "sampled"}`` — or None with
    ``MX_RQTRACE=0``.  Head-based sampling: the bit is decided HERE,
    once, and propagated, so one request is either traced on every hop
    or on none (``MX_RQTRACE_SAMPLE``, default 1.0).  Trace ids are 16
    hex chars of ``os.urandom`` — no coordination, no clock."""
    if not rqtrace_enabled():
        return None
    rate = _env_float("MX_RQTRACE_SAMPLE", 1.0) if sample is None \
        else float(sample)
    sampled = rate >= 1.0 or (rate > 0.0 and random.random() < rate)
    return {"trace_id": os.urandom(8).hex(), "sampled": sampled}


def format_trace_header(trace_id: str, parent_span_id: int = 0,
                        sampled: bool = True) -> str:
    return f"{trace_id};parent={int(parent_span_id)};" \
           f"sampled={1 if sampled else 0}"


def parse_trace_header(value: Optional[str]) -> Optional[dict]:
    """Parse an ``X-MX-Trace`` header into ``{"trace_id", "parent",
    "sampled"}``; garbage (wrong field count, non-int parent) returns
    None — an upstream that speaks a different dialect downgrades to
    untraced, never to a 500."""
    if not value:
        return None
    parts = value.strip().split(";")
    trace_id = parts[0].strip()
    if not trace_id or len(trace_id) > 64:
        return None
    out = {"trace_id": trace_id, "parent": 0, "sampled": True}
    for part in parts[1:]:
        key, _, raw = part.strip().partition("=")
        if key == "parent":
            try:
                out["parent"] = int(raw)
            except ValueError:
                return None
        elif key == "sampled":
            out["sampled"] = raw.strip() not in ("0", "false")
    return out


def _sampling_defaults() -> dict:
    """Fleet-wide sampling defaults, applied when a /generate body omits
    the field (docs/SERVING.md §Sampling) — an explicit body value
    always wins, and ``temperature: 0`` still means greedy."""
    return {"temperature": _env_float("MX_SERVE_TEMPERATURE", 0.0),
            "top_k": _env_int("MX_SERVE_TOP_K", 0),
            "top_p": _env_float("MX_SERVE_TOP_P", 1.0)}


def _send(handler, code: int, body, ctype: str = "application/json"):
    if not isinstance(body, (str, bytes)):
        body = json.dumps(body) + "\n"
    payload = body if isinstance(body, bytes) \
        else body.encode("utf-8", "replace")
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(payload)))
    handler.end_headers()
    handler.wfile.write(payload)


def _read_json_body(handler) -> dict:
    n = int(handler.headers.get("Content-Length") or 0)
    raw = handler.rfile.read(n) if n else b"{}"
    body = json.loads(raw.decode("utf-8"))
    if not isinstance(body, dict):
        raise ValueError("body must be a JSON object")
    return body


class _ReplicaHandler(BaseHTTPRequestHandler):
    """One replica's route handler.  mxlint JAX_FREE_ENTRIES starts its
    reachability scan here: handlers submit Requests and poll host-side
    stream flags — they never import jax, never force a device sync
    (the engine-driver thread owns the device)."""

    server_version = "mxnet-tpu-replica/1"

    def do_GET(self):  # noqa: N802 (http.server contract)
        rep: "ReplicaServer" = self.server.replica  # type: ignore
        route = self.path.split("?", 1)[0].rstrip("/") or "/"
        if route in ("/", "/statusz"):
            _send(self, 200, rep.statusz())
        elif route == "/healthz":
            snap = rep.healthz()
            _send(self, 200 if snap["ok"] else 503, snap)
        else:
            _send(self, 404, {"error": f"no such route {route!r}"})

    def do_POST(self):  # noqa: N802
        rep: "ReplicaServer" = self.server.replica  # type: ignore
        route = self.path.split("?", 1)[0].rstrip("/")
        if route == "/generate":
            self._generate(rep)
        elif route == "/admin/drain":
            rep.drain()
            _send(self, 200, {"draining": True, "rank": rep.rank})
        elif route == "/admin/undrain":
            rep.undrain()
            _send(self, 200, {"draining": False, "rank": rep.rank})
        else:
            _send(self, 404, {"error": f"no such route {route!r}"})

    def _generate(self, rep: "ReplicaServer"):
        if rep.draining:
            _send(self, 503, {"error": "replica draining",
                              "rank": rep.rank})
            return
        try:
            body = _read_json_body(self)
        except (ValueError, UnicodeDecodeError) as e:
            _send(self, 400, {"error": f"bad JSON body: {e}"})
            return
        trace = parse_trace_header(self.headers.get(TRACE_HEADER))
        try:
            result = rep.generate(body, trace=trace)
        except MXNetError as e:
            # backpressure (queue full) and validation errors are the
            # client's 4xx/503, never a replica crash
            code = 503 if "queue full" in str(e) else 400
            _send(self, code, {"error": str(e), "rank": rep.rank})
            return
        except TimeoutError as e:
            _send(self, 504, {"error": str(e), "rank": rep.rank})
            return
        _send(self, 200, result)

    def log_message(self, fmt, *args):
        _LOG.debug("%s %s", self.address_string(), fmt % args)


class ReplicaServer:
    """HTTP front for one ServingEngine (one replica of the fleet).

    The engine runs on a private driver thread; handler threads only
    submit/poll.  ``bos_id``/``eos_id`` are the defaults a /generate
    body may override per request."""

    def __init__(self, engine, bos_id: int, eos_id: int,
                 port: Optional[int] = None, host: Optional[str] = None,
                 rank: Optional[int] = None,
                 directory: Optional[str] = None):
        self.engine = engine
        self.bos_id = int(bos_id)
        self.eos_id = int(eos_id)
        self.rank = telemetry.rank() if rank is None else int(rank)
        self._host = host if host is not None \
            else os.environ.get("MX_SERVE_HOST", "127.0.0.1")
        if port is None:
            base = _env_int("MX_SERVE_PORT", 0)
            port = base + self.rank if base > 0 else 0
        self._bind_port = int(port)
        self._dir = directory if directory is not None \
            else os.environ.get("MX_TELEMETRY_DIR")
        self._server: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._drive_thread: Optional[threading.Thread] = None
        self._portfile: Optional[str] = None
        self._wake = threading.Condition()
        self._stop = False
        self.draining = False
        self._outstanding = 0
        self._error: Optional[str] = None
        self.port = 0

    # ---- lifecycle ---------------------------------------------------
    def start(self) -> "ReplicaServer":
        server = ThreadingHTTPServer((self._host, self._bind_port),
                                     _ReplicaHandler)
        server.daemon_threads = True
        server.replica = self  # type: ignore[attr-defined]
        self._server = server
        self.port = server.server_address[1]
        self._http_thread = threading.Thread(
            target=server.serve_forever, daemon=True,
            name=f"mx-serve-http-{self.rank}")
        self._http_thread.start()
        self._drive_thread = threading.Thread(
            target=self._drive, daemon=True,
            name=f"mx-serve-engine-{self.rank}")
        self._drive_thread.start()
        self._portfile = self._write_portfile()
        _LOG.info("replica %d serving on %s:%d", self.rank, self._host,
                  self.port)
        return self

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._drive_thread is not None:
            self._drive_thread.join(timeout=10.0)
        if self._portfile:
            try:
                os.unlink(self._portfile)
            except OSError:
                pass
            self._portfile = None

    def _write_portfile(self) -> Optional[str]:
        if not self._dir:
            return None
        path = serve_portfile_path(self._dir, self.rank)
        host = self._host
        payload = {"rank": self.rank, "port": self.port,
                   "host": "127.0.0.1" if host in ("0.0.0.0", "::", "")
                   else host,
                   "pid": os.getpid(), "time": round(time.time(), 3)}
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            os.makedirs(self._dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)  # the router never sees a torn file
        except OSError as e:
            _LOG.warning("serve portfile write to %s failed: %s", path, e)
            return None
        return path

    # ---- engine driver (the only jax-touching thread) ----------------
    def _drive(self) -> None:
        while True:
            with self._wake:
                while not self._stop and not self.engine._sched.depth:
                    self._wake.wait(timeout=0.5)
                if self._stop:
                    return
            try:
                self.engine.run()
            except Exception as e:  # noqa: BLE001 — surface via /healthz
                self._error = f"{type(e).__name__}: {e}"
                _LOG.exception("replica %d engine loop died", self.rank)
                return

    # ---- handler-side operations (jax-free) --------------------------
    def generate(self, body: dict, trace: Optional[dict] = None) -> dict:
        """Build + submit one Request and poll it to completion.

        ``trace`` is the parsed ``X-MX-Trace`` context the Router
        propagated; a direct client (no header) gets a replica-minted
        one so single-replica deployments still trace.  Sampled requests
        run inside a paired ``serve_handle`` span (the replica-side root
        of the request tree — its open begin is the "died inside X"
        clue); unsampled ones are measured anyway and the span recorded
        retroactively on an error or TTFT SLO breach."""
        defaults = _sampling_defaults()
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            raise MXNetError("/generate body needs a non-empty 'prompt' "
                             "list of token ids")
        if trace is None:
            trace = mint_trace()
        tid = trace["trace_id"] if trace else None
        sampled = bool(trace.get("sampled", True)) if trace else True
        upstream = int(trace.get("parent", 0)) if trace else 0
        req = Request(
            prompt,
            max_new_tokens=int(body.get("max_new_tokens", 16)),
            bos_id=int(body.get("bos_id", self.bos_id)),
            eos_id=int(body.get("eos_id", self.eos_id)),
            request_id=body.get("request_id"),
            temperature=float(body.get("temperature",
                                       defaults["temperature"])),
            top_k=int(body.get("top_k", defaults["top_k"])),
            top_p=float(body.get("top_p", defaults["top_p"])),
            seed=body.get("seed"),
            prefix=body.get("prefix"),
            session=body.get("session"),
            trace_id=tid, parent_span_id=upstream, sampled=sampled)
        timeout_s = float(body.get("timeout_s", 120.0))
        if tid and sampled and telemetry.spans_enabled():
            with telemetry.span("serve_handle", paired=True,
                                trace_id=tid, request_id=req.id,
                                replica=self.rank,
                                upstream_span=upstream):
                self._serve_wait(req, timeout_s)
        else:
            t0 = time.perf_counter()
            try:
                self._serve_wait(req, timeout_s)
            except BaseException as e:
                if tid:  # always-sample the tail: errors keep their span
                    telemetry.record_span(
                        "serve_handle", t0, time.perf_counter(),
                        trace_id=tid, request_id=req.id,
                        replica=self.rank, late_sampled=True,
                        error=type(e).__name__)
                raise
            slo = _env_float("MX_SERVE_SLO_TTFT_MS", 0.0)
            if tid and slo > 0 and req.ttft_ms > slo:
                telemetry.record_span(
                    "serve_handle", t0, time.perf_counter(),
                    trace_id=tid, request_id=req.id, replica=self.rank,
                    late_sampled=True, slo_stage="ttft")
        out = {"request_id": req.id,
               "tokens": [int(t) for t in req.stream],
               "finish_reason": req.stream.finish_reason,
               "replica": self.rank,
               "generation": self.engine.weight_generation,
               "session": req.session,
               "ttft_ms": round(req.ttft_ms, 3),
               "queue_wait_ms": round(req.queue_wait_ms, 3)}
        if tid:
            out["trace_id"] = tid
            out["sampled"] = sampled
        return out

    def _serve_wait(self, req: Request, timeout_s: float) -> None:
        self._outstanding += 1
        try:
            self.engine.submit(req)
            with self._wake:
                self._wake.notify_all()
            deadline = time.monotonic() + timeout_s
            while not req.stream.finished:
                if self._error:
                    raise MXNetError(
                        f"replica engine died: {self._error}")
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"request {req.id} not finished after "
                        f"{timeout_s:.0f}s")
                time.sleep(0.002)
        finally:
            self._outstanding -= 1

    def drain(self) -> None:
        self.draining = True
        telemetry.record("serve_drain", executor="ReplicaServer",
                         rank=self.rank)

    def undrain(self) -> None:
        self.draining = False
        telemetry.record("serve_undrain", executor="ReplicaServer",
                         rank=self.rank)

    def healthz(self) -> dict:
        return {"ok": self._error is None and not self.draining,
                "draining": self.draining, "error": self._error,
                "rank": self.rank, "outstanding": self._outstanding}

    def statusz(self) -> dict:
        return {"rank": self.rank, "draining": self.draining,
                "outstanding": self._outstanding, "error": self._error,
                "engine": self.engine.statusz_snapshot(),
                "time": round(time.time(), 3)}


class _RouterHandler(BaseHTTPRequestHandler):
    """Router routes — jax-free by construction (pure HTTP relay +
    host-side bookkeeping); mxlint JAX_FREE_ENTRIES scans from here."""

    server_version = "mxnet-tpu-router/1"

    def do_GET(self):  # noqa: N802
        router: "Router" = self.server.router  # type: ignore
        route = self.path.split("?", 1)[0].rstrip("/") or "/"
        if route in ("/", "/statusz"):
            _send(self, 200, router.statusz())
        elif route == "/healthz":
            snap = router.healthz()
            _send(self, 200 if snap["ok"] else 503, snap)
        elif route == "/tracez":
            _send(self, 200, router.tracez())
        else:
            _send(self, 404, {"error": f"no such route {route!r}"})

    def do_POST(self):  # noqa: N802
        router: "Router" = self.server.router  # type: ignore
        route, _, query = self.path.partition("?")
        route = route.rstrip("/")
        if route == "/generate":
            try:
                body = _read_json_body(self)
            except (ValueError, UnicodeDecodeError) as e:
                _send(self, 400, {"error": f"bad JSON body: {e}"})
                return
            code, payload = router.dispatch(body)
            _send(self, code, payload)
        elif route in ("/admin/drain", "/admin/undrain"):
            rank = None
            for part in query.split("&"):
                if part.startswith("rank="):
                    try:
                        rank = int(part[5:])
                    except ValueError:
                        pass
            if rank is None:
                _send(self, 400, {"error": "need ?rank=N"})
                return
            ok = router.set_drain(rank, route.endswith("/drain"))
            _send(self, 200 if ok else 404,
                  {"rank": rank, "draining": route.endswith("/drain"),
                   "ok": ok})
        else:
            _send(self, 404, {"error": f"no such route {route!r}"})

    def log_message(self, fmt, *args):
        _LOG.debug("%s %s", self.address_string(), fmt % args)


class Router:
    """Load-balancing front door over N replica servers.

    Discovery is portfile-based (``serve-port-*.json`` under
    ``directory``) and re-runs at every health poll, so replicas added
    by an ``--elastic`` rescale join rotation automatically and dead
    ones fall out.  Dispatch policy: session affinity first (an id seen
    before goes back to its replica while that replica is healthy —
    keeping its prefix-cache pages hot), otherwise least outstanding
    requests among healthy, undrained replicas."""

    def __init__(self, directory: str, port: Optional[int] = None,
                 host: Optional[str] = None,
                 health_sec: Optional[float] = None):
        self.directory = directory
        self._host = host if host is not None \
            else os.environ.get("MX_SERVE_HOST", "127.0.0.1")
        self._bind_port = _env_int("MX_SERVE_ROUTER_PORT", 0) \
            if port is None else int(port)
        self.health_sec = _env_float("MX_SERVE_HEALTH_SEC", 2.0) \
            if health_sec is None else float(health_sec)
        self._lock = threading.Lock()
        # rank -> {rank, host, port, url, healthy, draining, outstanding}
        self._replicas: Dict[int, dict] = {}
        self._sessions: Dict[str, int] = {}
        self._server: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.port = 0
        self.dispatched = 0
        self.failovers = 0
        # /tracez surfaces (§Request tracing): trace_id -> in-flight
        # request with its currently open span, + a bounded ring of the
        # last K completed request trees (attempt list = the span tree's
        # dispatch children, failovers included)
        self._inflight: Dict[str, dict] = {}
        self._completed: deque = deque(
            maxlen=max(1, _env_int("MX_RQTRACE_TRACEZ_K", 32)))
        self.refresh()

    # ---- lifecycle ---------------------------------------------------
    def start(self) -> "Router":
        server = ThreadingHTTPServer((self._host, self._bind_port),
                                     _RouterHandler)
        server.daemon_threads = True
        server.router = self  # type: ignore[attr-defined]
        self._server = server
        self.port = server.server_address[1]
        self._http_thread = threading.Thread(
            target=server.serve_forever, daemon=True,
            name="mx-serve-router-http")
        self._http_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="mx-serve-router-health")
        self._health_thread.start()
        _LOG.info("router serving on %s:%d over %d replica(s)",
                  self._host, self.port, len(self._replicas))
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)

    # ---- replica set -------------------------------------------------
    def refresh(self) -> None:
        """Re-discover replicas from portfiles: new ranks join rotation
        (healthy until a probe says otherwise), vanished ranks drop."""
        found = {r["rank"]: r for r in discover_replicas(self.directory)}
        with self._lock:
            for rank, info in found.items():
                cur = self._replicas.get(rank)
                url = f"http://{info['host']}:{info['port']}"
                if cur is None or cur["url"] != url:
                    self._replicas[rank] = {
                        "rank": rank, "url": url, "healthy": True,
                        "draining": False, "outstanding": 0}
            for rank in list(self._replicas):
                if rank not in found:
                    del self._replicas[rank]

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_sec):
            self.refresh()
            for rep in self.replicas():
                self._probe(rep)

    def _probe(self, rep: dict) -> None:
        try:
            with urllib.request.urlopen(rep["url"] + "/healthz",
                                        timeout=2.0) as resp:
                snap = json.load(resp)
            healthy, draining = True, bool(snap.get("draining"))
        except urllib.error.HTTPError as e:
            # 503 = alive but draining/erroring: keep it out of rotation
            # without forgetting it (undrain brings it straight back)
            try:
                snap = json.load(e)
            except (ValueError, OSError):
                snap = {}
            healthy, draining = False, bool(snap.get("draining"))
        except (OSError, ValueError):
            healthy, draining = False, False
        with self._lock:
            cur = self._replicas.get(rep["rank"])
            if cur is not None:
                cur["healthy"] = healthy
                cur["draining"] = draining

    def replicas(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._replicas.values()]

    # ---- dispatch ----------------------------------------------------
    def _pick(self, session: Optional[str], exclude) -> Optional[dict]:
        with self._lock:
            live = [r for r in self._replicas.values()
                    if r["healthy"] and not r["draining"]
                    and r["rank"] not in exclude]
            if not live:
                return None
            if session is not None:
                rank = self._sessions.get(session)
                for r in live:
                    if r["rank"] == rank:
                        return r
            pick = min(live, key=lambda r: (r["outstanding"], r["rank"]))
            if session is not None:
                # (re)pin — a failed-over session sticks to its NEW home
                self._sessions[session] = pick["rank"]
            return pick

    def dispatch(self, body: dict):
        """Route one /generate body; returns (http_code, payload).
        Connection-level failures mark the replica dead and fail the
        request over; HTTP-level errors (4xx validation, 503 back-
        pressure) are the replica's verdict and pass through.

        Tracing (§Request tracing): mints the trace context, wraps the
        whole residence in a paired ``serve_route`` span whose id rides
        the outgoing header as ``parent=``, tracks the request in the
        /tracez in-flight table, and archives it to the completed ring
        on the way out.  A failed-over request stays ONE trace — its
        attempt list (and span tree) just grows a second dispatch."""
        trace = mint_trace()
        if trace is None:  # MX_RQTRACE=0: the untraced fast path
            return self._dispatch_attempts(body, None, 0, None)
        tid = trace["trace_id"]
        entry = {"trace_id": tid, "request_id": body.get("request_id"),
                 "session": body.get("session"),
                 "sampled": trace["sampled"], "open_span": "serve_route",
                 "replica": None, "started": round(time.time(), 3),
                 "attempts": []}
        with self._lock:
            self._inflight[tid] = entry
        t0 = time.perf_counter()
        code, payload = None, None
        try:
            if trace["sampled"] and telemetry.spans_enabled():
                with telemetry.span(
                        "serve_route", paired=True, trace_id=tid,
                        request_id=body.get("request_id"),
                        session=body.get("session")) as sp:
                    code, payload = self._dispatch_attempts(
                        body, trace, sp.span_id, entry)
            else:
                code, payload = self._dispatch_attempts(
                    body, trace, 0, entry)
        finally:
            self._finish_trace(trace, entry, code, payload, t0,
                               time.perf_counter())
        return code, payload

    def _dispatch_attempts(self, body: dict, trace: Optional[dict],
                           parent_span: int, entry: Optional[dict]):
        """The pick→POST→failover loop (one iteration per attempt)."""
        session = body.get("session")
        timeout_s = float(body.get("timeout_s", 120.0))
        raw = json.dumps(body).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        tid = trace["trace_id"] if trace else None
        sampled = bool(trace.get("sampled", True)) if trace else False
        if trace is not None:
            headers[TRACE_HEADER] = format_trace_header(
                tid, parent_span, sampled)
        tried: set = set()
        while True:
            rep = self._pick(session, tried)
            if rep is None:
                return 503, {"error": "no healthy replica available",
                             "tried": sorted(tried)}
            tried.add(rep["rank"])
            req = urllib.request.Request(
                rep["url"] + "/generate", data=raw, headers=headers)
            attempt = {"rank": rep["rank"], "t0": time.perf_counter(),
                       "t1": None, "ms": 0.0, "error": None}
            if entry is not None:
                with self._lock:
                    entry["open_span"] = "serve_dispatch"
                    entry["replica"] = rep["rank"]
                    entry["attempts"].append(attempt)
            with self._lock:
                cur = self._replicas.get(rep["rank"])
                if cur is not None:
                    cur["outstanding"] += 1
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as resp:
                    payload = json.load(resp)
                self.dispatched += 1
                payload["routed_to"] = rep["rank"]
                return 200, payload
            except urllib.error.HTTPError as e:
                try:
                    payload = json.load(e)
                except (ValueError, OSError):
                    payload = {"error": f"replica HTTP {e.code}"}
                payload["routed_to"] = rep["rank"]
                attempt["error"] = f"HTTP {e.code}"
                return e.code, payload
            except (urllib.error.URLError, OSError) as e:
                # connection-level death: mark dead, fail over
                with self._lock:
                    cur = self._replicas.get(rep["rank"])
                    if cur is not None:
                        cur["healthy"] = False
                self.failovers += 1
                attempt["error"] = str(e)[:200]
                telemetry.record("serve_failover", executor="Router",
                                 rank=rep["rank"], error=str(e)[:200],
                                 trace_id=tid)
                telemetry.record_serve_cause(
                    "failover", trace_id=tid, rank=rep["rank"])
                _LOG.warning("replica %d unreachable (%s); failing over",
                             rep["rank"], e)
            finally:
                attempt["t1"] = time.perf_counter()
                attempt["ms"] = (attempt["t1"] - attempt["t0"]) * 1e3
                if tid and sampled:
                    attrs = {"trace_id": tid, "replica": rep["rank"]}
                    if attempt["error"]:
                        attrs["error"] = attempt["error"]
                    telemetry.record_span("serve_dispatch",
                                          attempt["t0"], attempt["t1"],
                                          **attrs)
                with self._lock:
                    cur = self._replicas.get(rep["rank"])
                    if cur is not None:
                        cur["outstanding"] = max(
                            0, cur["outstanding"] - 1)

    def _finish_trace(self, trace: dict, entry: dict,
                      code: Optional[int], payload, t0: float,
                      t1: float) -> None:
        """Archive one traced dispatch: /tracez completed-ring entry +
        retroactive span emission for an UNSAMPLED request that erred or
        breached the TTFT SLO (always-sample the tail)."""
        tid = trace["trace_id"]
        ttft = float(payload.get("ttft_ms", 0.0)) \
            if isinstance(payload, dict) else 0.0
        if isinstance(payload, dict):
            payload.setdefault("trace_id", tid)
        slo = _env_float("MX_SERVE_SLO_TTFT_MS", 0.0)
        breach = slo > 0 and ttft > slo
        errorish = code is None or code >= 500
        if not trace["sampled"] and (errorish or breach) \
                and telemetry.spans_enabled():
            telemetry.record_span(
                "serve_route", t0, t1, trace_id=tid,
                request_id=entry["request_id"], late_sampled=True,
                code=code)
            for a in entry["attempts"]:
                attrs = {"trace_id": tid, "replica": a["rank"],
                         "late_sampled": True}
                if a["error"]:
                    attrs["error"] = a["error"]
                telemetry.record_span("serve_dispatch", a["t0"],
                                      a["t1"] or t1, **attrs)
        done = {"trace_id": tid, "request_id": entry["request_id"]
                if entry["request_id"] is not None else
                (payload.get("request_id")
                 if isinstance(payload, dict) else None),
                "session": entry["session"], "code": code,
                "latency_ms": round((t1 - t0) * 1e3, 3),
                "ttft_ms": round(ttft, 3), "replica": entry["replica"],
                "sampled": trace["sampled"], "slo_breach": breach,
                "attempts": [{"rank": a["rank"],
                              "ms": round(a["ms"], 3),
                              "error": a["error"]}
                             for a in entry["attempts"]]}
        with self._lock:
            self._inflight.pop(tid, None)
            self._completed.append(done)

    # ---- admin + introspection ---------------------------------------
    def set_drain(self, rank: int, draining: bool) -> bool:
        """Forward drain/undrain to a replica and mirror the flag
        locally so rotation updates immediately (the health poll would
        get there eventually)."""
        with self._lock:
            rep = self._replicas.get(rank)
            url = rep["url"] if rep is not None else None
        if url is None:
            return False
        verb = "drain" if draining else "undrain"
        try:
            req = urllib.request.Request(f"{url}/admin/{verb}", data=b"")
            with urllib.request.urlopen(req, timeout=5.0):
                pass
        except (urllib.error.URLError, OSError) as e:
            _LOG.warning("drain forward to replica %d failed: %s",
                         rank, e)
            return False
        with self._lock:
            rep = self._replicas.get(rank)
            if rep is not None:
                rep["draining"] = draining
        return True

    def healthz(self) -> dict:
        reps = self.replicas()
        healthy = [r["rank"] for r in reps
                   if r["healthy"] and not r["draining"]]
        return {"ok": bool(healthy), "healthy": healthy,
                "replicas": len(reps)}

    def statusz(self) -> dict:
        with self._lock:
            sessions = len(self._sessions)
        return {"replicas": self.replicas(), "sessions": sessions,
                "dispatched": self.dispatched,
                "failovers": self.failovers,
                "health_sec": self.health_sec,
                "time": round(time.time(), 3)}

    def tracez(self) -> dict:
        """The /tracez payload (§Request tracing): the last K completed
        request trees (newest last; attempt list = dispatch spans,
        failovers included) and every in-flight request with its open
        span + elapsed — the fleet edition of the flight recorder's
        "died inside X" clue."""
        now = time.perf_counter()
        with self._lock:
            completed = [dict(c) for c in self._completed]
            inflight = []
            for e in self._inflight.values():
                open_t0 = (e["attempts"][-1]["t0"] if e["attempts"]
                           and e["open_span"] == "serve_dispatch"
                           else None)
                inflight.append({
                    "trace_id": e["trace_id"],
                    "request_id": e["request_id"],
                    "session": e["session"], "sampled": e["sampled"],
                    "open_span": e["open_span"],
                    "replica": e["replica"],
                    "started": e["started"],
                    "open_span_elapsed_ms": round(
                        (now - open_t0) * 1e3, 3)
                    if open_t0 is not None else None,
                    "attempts": len(e["attempts"])})
        return {"enabled": rqtrace_enabled(),
                "sample": _env_float("MX_RQTRACE_SAMPLE", 1.0),
                "k": self._completed.maxlen,
                "in_flight": inflight, "completed": completed,
                "time": round(time.time(), 3)}
