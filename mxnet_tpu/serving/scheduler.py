"""Continuous-batching request scheduler (docs/SERVING.md §Scheduler).

Requests enter a bounded FIFO queue and are admitted into fixed decode
*slots* BETWEEN decode steps — in-flight batching: a finished request
frees its slot (and its KV pages) at the next stream boundary and a
waiting request joins mid-flight, so the compiled decode step never idles
on ragged completion times.  The queue bound (``MX_SERVE_QUEUE``) is the
backpressure surface: a full queue rejects loudly instead of growing
without bound under overload (callers retry / shed upstream).

Policy is deliberately plain FCFS: requests admit in arrival order when
(a) a slot is free and (b) the paged KV pool can grant at least one page.
Fancier policies (shortest-prompt-first, priority lanes) slot in by
overriding :meth:`ContinuousBatchingScheduler.pop_ready`.
"""
from __future__ import annotations

import hashlib
import itertools
import os
import time
from collections import OrderedDict, deque
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["Request", "TokenStream", "ContinuousBatchingScheduler",
           "queue_bound", "PrefixCache", "prefix_key"]

_ids = itertools.count()


def queue_bound() -> int:
    """Request-queue bound, re-read from ``MX_SERVE_QUEUE`` per call
    (default 256; 0 = unbounded — load tests only)."""
    try:
        return max(0, int(os.environ.get("MX_SERVE_QUEUE", 256)))
    except (TypeError, ValueError):
        return 256


class TokenStream:
    """Per-request output stream: tokens append as the engine reads them
    back at stream cadence; ``finished`` flips when the request
    completes (EOS / token budget / eviction)."""

    def __init__(self):
        self.tokens: List[int] = []
        self.finished = False
        self.finish_reason: Optional[str] = None

    def append(self, tok: int) -> None:
        self.tokens.append(int(tok))

    def finish(self, reason: str) -> None:
        self.finished = True
        self.finish_reason = reason

    def asarray(self) -> np.ndarray:
        return np.asarray(self.tokens, np.int32)

    def __len__(self):
        return len(self.tokens)

    def __iter__(self):
        return iter(self.tokens)


class Request:
    """One decode request.

    ``tokens`` is the prompt — the source sentence for seq2seq models
    (prefill = encode), the prompt prefix for decoder-only models
    (prefill = fill the cache/buffer).  Generation starts from
    ``bos_id`` and stops at ``eos_id`` or after ``max_new_tokens``.

    Sampling (docs/SERVING.md §Sampling): ``temperature`` 0.0 (the
    default) is greedy — BITWISE identical to the engine's original
    greedy path; > 0 samples from the temperature-scaled distribution,
    optionally truncated by ``top_k`` (0 = off) and nucleus ``top_p``
    (1.0 = off).  ``seed`` pins the request's private RNG stream: the
    same request with the same seed reproduces the same tokens across
    engines, restarts and slot assignments (the per-request key is
    carried as per-slot device state).

    ``prefix`` (optional int32 tokens) is a decoder-side forced prefix:
    the engine teacher-forces it into the slot's KV pages before free
    decode starts, and — with the prefix cache on — shares those pages
    across requests with an identical (source, prefix) instead of
    recomputing them.  ``session`` is an opaque affinity id the router
    uses to pin a conversation to one replica.

    Trace context (docs/OBSERVABILITY.md §Request tracing):
    ``trace_id`` is the fleet-wide correlation id the Router minted (or
    the replica minted for direct clients) and propagated via the
    ``X-MX-Trace`` header; every serving span/event the engine emits for
    this request carries it, so the merged gang trace renders ONE
    cross-process tree per request.  ``parent_span_id`` is the upstream
    (router-side) span id, informational only — cross-process linking
    happens through flow events keyed on the trace id, never on local
    span ids.  ``sampled=False`` (head-based sampling, MX_RQTRACE_SAMPLE)
    suppresses the request's per-request SPANS; events and SLO
    accounting always run."""

    def __init__(self, tokens, max_new_tokens: int, bos_id: int,
                 eos_id: int, request_id: Optional[str] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None,
                 prefix=None, session: Optional[str] = None,
                 trace_id: Optional[str] = None, parent_span_id: int = 0,
                 sampled: bool = True):
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        self.bos_id = int(bos_id)
        self.eos_id = int(eos_id)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        if self.temperature < 0.0:
            raise MXNetError("temperature must be >= 0 (0 = greedy)")
        if self.top_k < 0:
            raise MXNetError("top_k must be >= 0 (0 = off)")
        if not (0.0 < self.top_p <= 1.0):
            raise MXNetError("top_p must be in (0, 1] (1.0 = off)")
        self.seed = None if seed is None else int(seed)
        self.prefix = (np.zeros((0,), np.int32) if prefix is None
                       else np.asarray(prefix, np.int32).reshape(-1))
        self.session = session
        self.trace_id = trace_id
        self.parent_span_id = int(parent_span_id)
        self.sampled = bool(sampled)
        # cause-attribution breadcrumbs the engine stamps as the request
        # moves: preemption count, prefix-cache verdict (None = no prefix
        # candidate), and the weight generation that admitted it — the
        # inputs to the per-request `cause` field on serve_request
        self.preemptions = 0
        self.prefix_hit: Optional[bool] = None
        self.generation_at_admit: Optional[int] = None
        self.id = request_id if request_id is not None \
            else f"req{next(_ids)}"
        self.stream = TokenStream()
        # SLO telemetry stamps (perf_counter; wall deltas only).
        # t_first_token is stamped at the STREAM BOUNDARY that read the
        # first token back (burst-cadence resolution — the engine never
        # blocks per token), measured against t_submit: the user-visible
        # SUBMISSION-to-first-token TTFT (queue wait included), across
        # preemptions.
        # t_queue_start is the start of the CURRENT queue residence —
        # submit time, re-stamped by requeue() after a preemption — so
        # the serve_queue trace span covers only the latest queue leg,
        # never the first admission's prefill+decode.
        self.t_submit: Optional[float] = None
        self.t_queue_start: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.prefill_ms: float = 0.0
        # accumulated TRUE queue residence across admissions: each
        # pop_ready adds its leg (t_queue_start -> t_admit), so a
        # preempted request's first service period never counts as
        # "queue wait" — the serve_queue spans and this number agree
        self.queue_ms_acc: float = 0.0

    @property
    def ttft_ms(self) -> float:
        if self.t_submit is None or self.t_first_token is None:
            return 0.0
        return (self.t_first_token - self.t_submit) * 1e3

    @property
    def queue_wait_ms(self) -> float:
        return self.queue_ms_acc

    def __repr__(self):
        return (f"<Request {self.id} prompt={len(self.tokens)} "
                f"max_new={self.max_new_tokens} "
                f"out={len(self.stream)}"
                f"{' done' if self.stream.finished else ''}>")


class ContinuousBatchingScheduler:
    """Bounded FIFO of waiting requests + the admission policy."""

    def __init__(self, bound: Optional[int] = None):
        self._bound = bound
        self._q: deque = deque()

    @property
    def bound(self) -> int:
        return queue_bound() if self._bound is None else self._bound

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, request: Request) -> Request:
        """Enqueue a request; raises MXNetError when the queue is full
        (the documented backpressure contract — shed upstream)."""
        bound = self.bound
        if bound and len(self._q) >= bound:
            raise MXNetError(
                f"serving queue full ({len(self._q)}/{bound} waiting): "
                "raise MX_SERVE_QUEUE or shed load upstream")
        request.t_submit = time.perf_counter()
        request.t_queue_start = request.t_submit
        self._q.append(request)
        return request

    def requeue(self, request: Request) -> None:
        """Return a preempted request to the HEAD of the queue (pool
        pressure evicted it mid-decode; it must not lose its place or be
        dropped by the bound — preemption is the engine's problem, not
        the client's)."""
        request.t_queue_start = time.perf_counter()
        self._q.appendleft(request)

    def pop_ready(self, free_slots: int, pages_free: int,
                  page_size: int) -> List[Request]:
        """FCFS admissions for this stream boundary: up to ``free_slots``
        requests, stopping when the paged pool cannot grant a first page
        to the next head-of-line request (no skip-ahead: later, smaller
        requests must not starve the head)."""
        out: List[Request] = []
        budget = pages_free
        while self._q and len(out) < free_slots and budget >= 1:
            req = self._q.popleft()
            req.t_admit = time.perf_counter()
            if req.t_queue_start is not None:
                req.queue_ms_acc += (req.t_admit - req.t_queue_start) * 1e3
            out.append(req)
            budget -= 1  # reserve the first page; later pages grow on
            #              demand per dispatch burst (engine._ensure_pages)
        return out


# ---------------------------------------------------------------------------
# prefix cache index (docs/SERVING.md §Prefix cache)
# ---------------------------------------------------------------------------
def prefix_key(*parts) -> str:
    """Stable token-hash key for a prefix-cache entry.  Parts are ints,
    strings or int arrays (token vectors); the digest is restart-stable
    (content only, no object ids)."""
    h = hashlib.sha1()
    for p in parts:
        if isinstance(p, np.ndarray):
            h.update(b"a" + np.ascontiguousarray(p, np.int64).tobytes())
        else:
            h.update(b"s" + repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()


class PrefixCache:
    """LRU token-hash index over reusable prefill work (host-side
    bookkeeping only — payloads are opaque to this class).

    Two entry kinds share the index: ``"pages"`` entries point at KV
    pages in the :class:`~.paged_cache.PagedKVCache` that hold a
    teacher-forced decoder prefix (the engine adopts/copies them on a
    hit instead of re-ingesting), and ``"prefill"`` entries hold device
    copies of the prefill executable's per-slot output rows (e.g. the
    encoder memory for a seq2seq source) so a repeated source skips the
    prefill dispatch entirely.

    Every entry is stamped with the engine's weight generation at
    insert: a hot-swap bumps the generation, and ``invalidate_stale``
    drops every entry from an older generation at the flip — a post-swap
    request can never fork KV pages computed under old weights
    (docs/SERVING.md §Weight hot-swap).

    Eviction: ``put`` bounds the index at ``max_entries`` (LRU), and the
    engine calls ``pop_lru("pages")`` under pool pressure BEFORE falling
    back to recompute-preemption of a live request.  Dropped entries are
    RETURNED to the caller, which owns freeing any allocator pages they
    reference."""

    def __init__(self, max_entries: int = 64):
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self):
        return len(self._entries)

    def get(self, key: str, generation: int) -> Optional[dict]:
        """Look up ``key``; counts a hit only for a same-generation
        entry.  A stale-generation entry is treated as (and counted as)
        a miss — the caller re-ingests and ``put`` replaces it."""
        e = self._entries.get(key)
        if e is not None and e["generation"] == generation:
            self._entries.move_to_end(key)
            e["uses"] += 1
            self.hits += 1
            return e
        self.misses += 1
        return None

    def put(self, key: str, kind: str, generation: int,
            payload: dict) -> List[dict]:
        """Insert/replace an entry; returns the entries displaced by the
        LRU bound (plus any same-key predecessor) for the caller to
        release."""
        dropped = []
        old = self._entries.pop(key, None)
        if old is not None:
            dropped.append(old)
        self._entries[key] = {"key": key, "kind": kind,
                              "generation": int(generation),
                              "payload": payload, "uses": 0}
        while len(self._entries) > self.max_entries:
            _, e = self._entries.popitem(last=False)
            dropped.append(e)
        return dropped

    def pop_lru(self, kind: Optional[str] = None) -> Optional[dict]:
        """Drop and return the least-recently-used entry (optionally of
        one kind) — the engine's evict-before-preempt lever."""
        for key, e in self._entries.items():
            if kind is None or e["kind"] == kind:
                return self._entries.pop(key)
        return None

    def invalidate_stale(self, generation: int) -> List[dict]:
        """Drop every entry older than ``generation`` (the weight-swap
        flip).  Returns the dropped entries for page release."""
        stale = [k for k, e in self._entries.items()
                 if e["generation"] != generation]
        return [self._entries.pop(k) for k in stale]

    def clear(self) -> List[dict]:
        dropped = list(self._entries.values())
        self._entries.clear()
        return dropped
