"""Continuous-batching request scheduler (docs/SERVING.md §Scheduler).

Requests enter a bounded FIFO queue and are admitted into fixed decode
*slots* BETWEEN decode steps — in-flight batching: a finished request
frees its slot (and its KV pages) at the next stream boundary and a
waiting request joins mid-flight, so the compiled decode step never idles
on ragged completion times.  The queue bound (``MX_SERVE_QUEUE``) is the
backpressure surface: a full queue rejects loudly instead of growing
without bound under overload (callers retry / shed upstream).

Policy is deliberately plain FCFS: requests admit in arrival order when
(a) a slot is free and (b) the paged KV pool can grant at least one page.
Fancier policies (shortest-prompt-first, priority lanes) slot in by
overriding :meth:`ContinuousBatchingScheduler.pop_ready`.
"""
from __future__ import annotations

import itertools
import os
import time
from collections import deque
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["Request", "TokenStream", "ContinuousBatchingScheduler",
           "queue_bound"]

_ids = itertools.count()


def queue_bound() -> int:
    """Request-queue bound, re-read from ``MX_SERVE_QUEUE`` per call
    (default 256; 0 = unbounded — load tests only)."""
    try:
        return max(0, int(os.environ.get("MX_SERVE_QUEUE", 256)))
    except (TypeError, ValueError):
        return 256


class TokenStream:
    """Per-request output stream: tokens append as the engine reads them
    back at stream cadence; ``finished`` flips when the request
    completes (EOS / token budget / eviction)."""

    def __init__(self):
        self.tokens: List[int] = []
        self.finished = False
        self.finish_reason: Optional[str] = None

    def append(self, tok: int) -> None:
        self.tokens.append(int(tok))

    def finish(self, reason: str) -> None:
        self.finished = True
        self.finish_reason = reason

    def asarray(self) -> np.ndarray:
        return np.asarray(self.tokens, np.int32)

    def __len__(self):
        return len(self.tokens)

    def __iter__(self):
        return iter(self.tokens)


class Request:
    """One decode request.

    ``tokens`` is the prompt — the source sentence for seq2seq models
    (prefill = encode), the prompt prefix for decoder-only models
    (prefill = fill the cache/buffer).  Generation starts from
    ``bos_id`` and stops at ``eos_id`` or after ``max_new_tokens``."""

    def __init__(self, tokens, max_new_tokens: int, bos_id: int,
                 eos_id: int, request_id: Optional[str] = None):
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        self.bos_id = int(bos_id)
        self.eos_id = int(eos_id)
        self.id = request_id if request_id is not None \
            else f"req{next(_ids)}"
        self.stream = TokenStream()
        # SLO telemetry stamps (perf_counter; wall deltas only).
        # t_first_token is stamped at the STREAM BOUNDARY that read the
        # first token back (burst-cadence resolution — the engine never
        # blocks per token), measured against t_submit: the user-visible
        # SUBMISSION-to-first-token TTFT (queue wait included), across
        # preemptions.
        # t_queue_start is the start of the CURRENT queue residence —
        # submit time, re-stamped by requeue() after a preemption — so
        # the serve_queue trace span covers only the latest queue leg,
        # never the first admission's prefill+decode.
        self.t_submit: Optional[float] = None
        self.t_queue_start: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.prefill_ms: float = 0.0
        # accumulated TRUE queue residence across admissions: each
        # pop_ready adds its leg (t_queue_start -> t_admit), so a
        # preempted request's first service period never counts as
        # "queue wait" — the serve_queue spans and this number agree
        self.queue_ms_acc: float = 0.0

    @property
    def ttft_ms(self) -> float:
        if self.t_submit is None or self.t_first_token is None:
            return 0.0
        return (self.t_first_token - self.t_submit) * 1e3

    @property
    def queue_wait_ms(self) -> float:
        return self.queue_ms_acc

    def __repr__(self):
        return (f"<Request {self.id} prompt={len(self.tokens)} "
                f"max_new={self.max_new_tokens} "
                f"out={len(self.stream)}"
                f"{' done' if self.stream.finished else ''}>")


class ContinuousBatchingScheduler:
    """Bounded FIFO of waiting requests + the admission policy."""

    def __init__(self, bound: Optional[int] = None):
        self._bound = bound
        self._q: deque = deque()

    @property
    def bound(self) -> int:
        return queue_bound() if self._bound is None else self._bound

    @property
    def depth(self) -> int:
        return len(self._q)

    def submit(self, request: Request) -> Request:
        """Enqueue a request; raises MXNetError when the queue is full
        (the documented backpressure contract — shed upstream)."""
        bound = self.bound
        if bound and len(self._q) >= bound:
            raise MXNetError(
                f"serving queue full ({len(self._q)}/{bound} waiting): "
                "raise MX_SERVE_QUEUE or shed load upstream")
        request.t_submit = time.perf_counter()
        request.t_queue_start = request.t_submit
        self._q.append(request)
        return request

    def requeue(self, request: Request) -> None:
        """Return a preempted request to the HEAD of the queue (pool
        pressure evicted it mid-decode; it must not lose its place or be
        dropped by the bound — preemption is the engine's problem, not
        the client's)."""
        request.t_queue_start = time.perf_counter()
        self._q.appendleft(request)

    def pop_ready(self, free_slots: int, pages_free: int,
                  page_size: int) -> List[Request]:
        """FCFS admissions for this stream boundary: up to ``free_slots``
        requests, stopping when the paged pool cannot grant a first page
        to the next head-of-line request (no skip-ahead: later, smaller
        requests must not starve the head)."""
        out: List[Request] = []
        budget = pages_free
        while self._q and len(out) < free_slots and budget >= 1:
            req = self._q.popleft()
            req.t_admit = time.perf_counter()
            if req.t_queue_start is not None:
                req.queue_ms_acc += (req.t_admit - req.t_queue_start) * 1e3
            out.append(req)
            budget -= 1  # reserve the first page; later pages grow on
            #              demand per dispatch burst (engine._ensure_pages)
        return out
