"""Draft proposers for speculative decoding (docs/SERVING.md
§Speculative decoding).

Speculative decoding splits a decode step in two: a cheap host-side
*draft* proposes up to K next tokens, and the engine's ("verify", K)
executable teacher-forces all K through the target model in ONE ragged
paged decode dispatch — per-slot accepted-token counts are device
values, exactly the per-slot length masking the ragged paged-attention
design (PAPERS.md 2604.15464) already handles.  Standard
accept/resample (Leviathan et al.) keeps the OUTPUT DISTRIBUTION
identical to non-speculative sampling, and under greedy decode
(temperature 0) acceptance is argmax-equality so the emitted stream is
BITWISE identical to the plain decode path (tests/test_serving_sampling
asserts it at K in {1, 4}).

A draft is anything with ``propose(request, generated, k)`` returning
up to ``k`` int token ids — the engine never traces it, so drafts can
be arbitrary host code: an n-gram table, a distilled model running
eagerly, a grammar.  The default :class:`NGramDraft` is prompt-lookup
decoding (He et al., "LLMA"): match the tail of what has been generated
against the request's own prompt/prefix/history and propose the
continuation — free to compute, surprisingly effective on the copy-like
spans real serving traffic is full of (quotes, code edits, retrieval).
"""
from __future__ import annotations

from typing import List, Sequence

__all__ = ["DraftProposer", "NGramDraft", "traced_propose"]


def traced_propose(draft: "DraftProposer", request,
                   generated: Sequence[int], k: int) -> List[int]:
    """Call ``draft.propose`` and, for a sampled traced request, stamp a
    ``spec_draft`` event naming the trace (docs/OBSERVABILITY.md
    §Request tracing).  The engine routes every proposal through this
    seam so draft implementations stay arbitrary telemetry-free host
    code — the ``propose`` contract above is unchanged."""
    out = draft.propose(request, generated, k)
    tid = getattr(request, "trace_id", None)
    if tid and getattr(request, "sampled", True):
        from .. import telemetry

        if telemetry.spans_enabled():
            telemetry.record("spec_draft", trace_id=tid,
                             request_id=request.id,
                             proposed=len(out))
    return out


class DraftProposer:
    """Host-side draft interface for the engine's speculative mode."""

    def propose(self, request, generated: Sequence[int],
                k: int) -> List[int]:
        """Up to ``k`` proposed next tokens for ``request`` given the
        tokens ``generated`` so far (free-decode tokens only — the
        forced prefix is on ``request.prefix``).  Fewer (or zero)
        proposals are always legal: the verify step treats the proposal
        count as a per-slot ragged length."""
        raise NotImplementedError


class NGramDraft(DraftProposer):
    """Prompt-lookup drafting: propose the continuation of the most
    recent place the current ``n``-gram tail occurred earlier in the
    request's own token history (prompt + forced prefix + generated).

    ``include_prompt`` folds ``request.tokens`` into the lookup pool —
    right for decoder-only prompts and for copy/transform tasks where
    source and target share a vocabulary; turn it off for seq2seq
    models whose source ids live in a different vocabulary."""

    def __init__(self, n: int = 2, include_prompt: bool = True):
        if n < 1:
            raise ValueError("NGramDraft needs n >= 1")
        self.n = int(n)
        self.include_prompt = bool(include_prompt)

    def propose(self, request, generated: Sequence[int],
                k: int) -> List[int]:
        pool: List[int] = []
        if self.include_prompt:
            pool.extend(int(t) for t in request.tokens)
        pool.extend(int(t) for t in getattr(request, "prefix", ()))
        pool.extend(int(t) for t in generated)
        for n in range(min(self.n, len(pool)), 0, -1):
            tail = pool[-n:]
            # most recent earlier occurrence wins (locality: recent
            # context repeats more than distant context)
            for start in range(len(pool) - n - 1, -1, -1):
                if pool[start:start + n] == tail:
                    nxt = pool[start + n:start + n + k]
                    if nxt:
                        return nxt
        return []
