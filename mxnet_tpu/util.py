"""Misc utilities (reference: python/mxnet/util.py)."""
from __future__ import annotations

import functools
import inspect

__all__ = ["is_np_array", "is_np_shape", "set_np", "reset_np", "use_np",
           "makedirs", "get_gpu_count", "get_gpu_memory"]


def is_np_array() -> bool:
    """Deprecated numpy-array semantics switch (2.x); always False in 1.x."""
    return False


def is_np_shape() -> bool:
    return False


def set_np(shape=True, array=True):
    raise NotImplementedError(
        "mx.np semantics are a 2.x feature; this framework tracks the 1.x API")


def reset_np():
    pass


def use_np(func):
    return func


def makedirs(d):
    import os

    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    from .context import num_gpus

    return num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    from .context import gpu

    stats = gpu(gpu_dev_id).memory_stats() or {}
    free = stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)
    return free, stats.get("bytes_limit", 0)
