"""Stateful RNG facade over jax's functional keys.

Reference parity: mx.random.seed (python/mxnet/random.py) over per-device
Philox generators (include/mxnet/random_generator.h ~L100, ResourceRequest::
kRandom).

Design: a process-global key is split on every sampling call — the MXNet
"stateful RNG resource" becomes a counter-free key chain.  Inside a
HybridBlock trace there is no concrete key; the CachedOp threads a key
argument through the traced function and installs a *trace key provider*
here, so ops like Dropout stay pure and cache-friendly.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["seed", "next_key", "set_trace_key_provider"]


class _State(threading.local):
    def __init__(self):
        self.key = None
        self.trace_provider = None


_state = _State()
_DEFAULT_SEED = 0


def _jax():
    import jax

    return jax


def seed(seed_state: Optional[int] = None, ctx="all") -> None:
    """Seed the global generator (reference: mx.random.seed).

    Also seeds numpy's global RNG: initializers sample on the host via
    numpy (the reference's CPU-side init path is likewise governed by
    mx.random.seed), so reseeding must make parameter init reproducible."""
    if seed_state is None:
        seed_state = int(time.time() * 1e6) & 0x7FFFFFFF
    _state.key = _jax().random.PRNGKey(int(seed_state))
    import numpy as np

    np.random.seed(int(seed_state) & 0x7FFFFFFF)


class _TraceKeyProvider:
    """Splits keys off a traced key argument during CachedOp tracing."""

    def __init__(self, key_tracer):
        self._key = key_tracer
        self.used = False

    def next(self):
        jax = _jax()
        self.used = True
        self._key, sub = jax.random.split(self._key)
        return sub


def set_trace_key_provider(provider) -> Optional[_TraceKeyProvider]:
    prev = _state.trace_provider
    _state.trace_provider = provider
    return prev


def in_trace() -> bool:
    return _state.trace_provider is not None


def next_key():
    """Next RNG key: concrete in eager mode, traced inside a CachedOp trace."""
    if _state.trace_provider is not None:
        return _state.trace_provider.next()
    if _state.key is None:
        _state.key = _jax().random.PRNGKey(_DEFAULT_SEED)
    _state.key, sub = _jax().random.split(_state.key)
    return sub
