"""Precision subsystem (docs/PRECISION.md): graph-level AMP, traced
dynamic loss scaling, and calibrated int8 serving.

Three pillars over the compiled train/serve paths:

  * ``amp_pass`` — a cast-policy rewrite applied at trace time inside
    ``DataParallelStep._build``: per-op-class dispositions (matmul/conv
    compute in bf16, softmax/norm/reductions widen to f32) carried by a
    serializable :class:`~mxnet_tpu.precision.config.AmpPolicy` on the
    :class:`~mxnet_tpu.parallel.plan.Plan`;
  * ``loss_scale`` — the dynamic loss-scale state machine as device
    values inside the jitted step (scale/growth/skip state in the train
    state, non-finite steps become traced no-op updates, no host
    readback in any hot path);
  * ``quantize`` — post-training int8 for the serving engine: calibrated
    per-layer scales (reusing ``contrib/quantization``'s calibrators)
    rewrite Dense/Conv in the adapter's traced prefill/decode graphs
    onto the ``ops/quantization.py`` int8 primitives — ONE quantized
    decode executable, AOT-fingerprinted by the quant config.

Env surface (env_vars.py): MX_AMP, MX_AMP_POLICY, MX_LOSS_SCALE,
MX_QUANTIZE, MX_QUANT_CALIB, MX_SERVE_INT4, MX_QUANT_GROUP (all the
quant/AMP rewrites are registered graph passes — see ``passes/``).
"""
from .config import (AmpPolicy, LossScaleConfig, PrecisionConfig,
                     DEFAULT_LOW_OPS, DEFAULT_WIDEN_OPS)
from .amp_pass import apply_amp
from .runtime import amp_scope, quant_scope, quant_entry
from . import loss_scale
from .quantize import (QuantizedAdapter, quantize_adapter,
                       maybe_quantize_adapter, Int4WeightAdapter,
                       int4_adapter, maybe_int4_adapter)

__all__ = ["AmpPolicy", "LossScaleConfig", "PrecisionConfig",
           "DEFAULT_LOW_OPS", "DEFAULT_WIDEN_OPS", "apply_amp",
           "amp_scope", "quant_scope", "quant_entry", "loss_scale",
           "QuantizedAdapter", "quantize_adapter",
           "maybe_quantize_adapter", "Int4WeightAdapter",
           "int4_adapter", "maybe_int4_adapter"]
