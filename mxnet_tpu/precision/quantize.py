"""Calibrated int8 serving: rewrite Dense/Conv layers inside the serving
engine's traced prefill/decode graphs onto the ``ops/quantization.py``
int8 primitives.

``contrib/quantization.py`` already owns post-training calibration (the
naive/entropy ``_Calibrator`` over a ``_StreamingHist``) and eager
``QuantizedDense``/``QuantizedConv2D`` twins — but those re-dispatch
eagerly per layer per call, which is exactly the per-op overhead the
serving engine exists to remove.  This module produces a
:class:`QuantizedAdapter`: a wrapper around any
:class:`~mxnet_tpu.serving.engine.ServingAdapter` whose ``decode``/
``prefill`` run the SAME traced graphs as the wrapped adapter, except
every selected Dense/Conv layer lowers to int8 matmul/conv with int32
accumulation (MXU ``preferred_element_type=int32``) — so the engine
still books exactly ONE decode executable, now carrying the quantized
program (the *Tensor Processing Primitives* argument, arXiv:2104.05755,
applied as a TVM-style graph rewrite, arXiv:1802.04799).

Mechanics: the adapter pre-quantizes each selected layer's weight to an
int8 device buffer (params-bytes is where int8 serving pays off) and
activates :func:`~mxnet_tpu.precision.runtime.quant_scope` around the
wrapped adapter's traced bodies; ``gluon.nn.Dense``/``Conv2D`` consult
the scope in ``hybrid_forward`` and route through the int8 twin.
Activation ranges come from calibration (``calibrate``), observed via
eager forward-pre hooks exactly as ``contrib.quantization.quantize_net``
does.

The quantization signature (calib mode + per-layer thresholds) joins the
adapter ``signature()`` and therefore the engine's AOT-cache
fingerprint: a restart under different ``MX_QUANTIZE``/``MX_QUANT_CALIB``
settings *misses* instead of deserializing the wrong program.  Int8
buffers register under the ``quantized`` memwatch census category.

The int4 path (:class:`Int4WeightAdapter`) lives next to int8: weight-
ONLY quantization — Dense/Conv weights packed 2 per byte with group-wise
f16 scales (``MX_QUANT_GROUP``), dequantized IN-TRACE by
``_contrib_dequantize_int4`` inside the engine's compiled decode/prefill
bodies.  No activation quantization, hence no calibration: decode is
weight-bandwidth bound, and ~0.14x weight bytes is the win.

Both adapters express their rewrite as a registered graph pass
(``passes/builtin``: ``quant_int8`` / ``quant_int4``) exposed via
``.passes`` — the serving engine builds its pipeline from that, and the
pass signature is what joins the AOT-cache fingerprint.

Env surface: ``MX_QUANTIZE`` (``int8`` to enable, ``0``/unset off) with
``MX_QUANT_CALIB`` (``naive``/``entropy``, default naive) drives
:func:`maybe_quantize_adapter`; ``MX_SERVE_INT4`` (``1``/``int4`` on)
with ``MX_QUANT_GROUP`` (group size, default 32, even) drives
:func:`maybe_int4_adapter`.
"""
from __future__ import annotations

import hashlib
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..base import MXNetError


def _calib_tools():
    """contrib.quantization's calibrators, resolved lazily: this module
    sits on the package's import spine (precision/__init__ loads before
    ndarray finishes importing), and contrib pulls in the ONNX subsystem
    at package level."""
    from ..contrib import quantization as cq

    return cq

__all__ = ["QuantizedAdapter", "quantize_adapter", "maybe_quantize_adapter",
           "Int4WeightAdapter", "int4_adapter", "maybe_int4_adapter",
           "collect_quantizable", "calibrate"]


def collect_quantizable(block, exclude: Iterable[str] = ()) -> List[Tuple]:
    """[(path, layer)] for every Dense/Conv2D reachable from ``block``
    (depth-first over ``_children``, any container shape — unlike the
    sequential-only ``quantize_net`` walker, the serving rewrite never
    replays children, so composite blocks are safe)."""
    from ..gluon import nn as gnn

    exclude = set(exclude or ())
    out: List[Tuple] = []

    def walk(blk, path):
        for key, child in blk._children.items():
            p = f"{path}.{key}" if path else str(key)
            if isinstance(child, gnn.Conv2D):
                # ops/quantization.quantized_conv is NC-first; a
                # channel-last conv stays f32, conservatively
                layout = child._kwargs.get("layout") or "NCHW"
                if layout == "NCHW" and p not in exclude \
                        and child.name not in exclude:
                    out.append((p, child))
            elif isinstance(child, gnn.Dense):
                if p not in exclude and child.name not in exclude:
                    out.append((p, child))
            else:
                walk(child, p)

    walk(block, "")
    return out


def calibrate(layers: List[Tuple], calib_data, calib_fn: Callable,
              calib_mode: str = "naive",
              num_calib_batches: Optional[int] = None,
              root=None) -> Dict[str, float]:
    """Observe per-layer input activations over ``calib_data`` ->
    {path: threshold}.  ``calib_fn(batch)`` runs one representative
    eager forward (e.g. a greedy ``translate`` over a prompt batch);
    forward-pre hooks on the target layers feed the calibrator —
    identical mechanics to ``quantize_net``'s eager calibration pass,
    including the hybridization handling: pass ``root`` (the block
    ``calib_fn`` forwards through) so ``hybridize()``d blocks are
    deactivated for the pass — forward-pre hooks never fire through a
    CachedOp fast path, and a hooked-but-unobserved layer would raise
    at ``threshold()`` below."""
    from .. import autograd

    if calib_mode not in ("naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r} "
                         "(naive/entropy)")
    cq = _calib_tools()
    calib = cq._Calibrator(calib_mode)
    hooks = []
    for path, layer in layers:
        hook = (lambda pp: lambda blk, args: calib.observe(
            pp, args[0].asnumpy()))(path)
        layer.register_forward_pre_hook(hook)
        hooks.append((layer, hook))
    hybridized = cq._active_blocks(root, []) if root is not None else []
    for b in hybridized:
        b._active = False
    try:
        with autograd.pause():
            for i, batch in enumerate(calib_data):
                calib_fn(batch)
                if num_calib_batches and i + 1 >= num_calib_batches:
                    break
    finally:
        for layer, hook in hooks:
            layer._forward_pre_hooks.remove(hook)
        for b in hybridized:
            b._active = True
    thresholds = {}
    for path, _layer in layers:
        t = calib.threshold(path)
        cq.check_calibrated_threshold(path, calib_mode,
                                      calib.minmax[path], t)
        thresholds[path] = t
    return thresholds


class _TracedTwin:
    """Traced int8 twin of one ``gluon.nn.Dense``/``Conv2D``: wraps the
    eager contrib twin (``QuantizedDense``/``QuantizedConv2D`` — the ONE
    copy of the calibrated quantize -> int8 kernel -> dequantize ->
    activation lowering lives in their F-generic ``_forward``) with the
    facts the serving rewrite needs: the layer path, the signature
    thresholds, byte accounting, and the traced-call contract
    ``twin(F, x, bias)`` where ``bias`` is the layer's own traced
    parameter (the impl's snapshot bias — zeros for bias-less layers —
    is the fallback, a device constant of the traced graph like the
    int8 weight, which is the params-bytes win)."""

    def __init__(self, impl, path: str, act_thresh: Optional[float]):
        self._impl = impl
        self.path = path
        self.act_thresh = act_thresh
        self._w_thresh = impl._w_thresh
        self.orig_nbytes = impl.orig_nbytes
        self.nbytes = impl.nbytes

    def arrays(self):
        i = self._impl
        return [i._qweight._data, i._w_min._data, i._w_max._data]

    def __call__(self, F, x, bias):
        return self._impl._forward(
            F, x, bias if bias is not None else self._impl._bias)


class _Int4Twin:
    """Traced int4 twin of one Dense/Conv2D: wraps the weight-only
    contrib impl (``Int4Dense``/``Int4Conv2D`` — the one copy of the
    dequantize-in-trace lowering) with the layer path, a content digest
    of the packed buffers (the restart-stable signature component — no
    thresholds exist on a weight-only path), and byte accounting."""

    def __init__(self, impl, path: str):
        self._impl = impl
        self.path = path
        h = hashlib.sha256()
        h.update(impl._packed.asnumpy().tobytes())
        h.update(impl._scales.asnumpy().tobytes())
        self.digest = h.hexdigest()[:16]
        self.orig_nbytes = impl.orig_nbytes
        self.nbytes = impl.nbytes

    def arrays(self):
        i = self._impl
        return [i._packed._data, i._scales._data]

    def __call__(self, F, x, bias):
        return self._impl._forward(
            F, x, bias if bias is not None else self._impl._bias)


def _quantized_arrays(adapter):
    """memwatch provider: the quantized weight buffers + scale/range
    constants the adapter holds resident (the `quantized` census
    slice — int8 and int4 adapters both land here)."""
    out = []
    for entry in adapter._entries.values():
        out.extend(entry.arrays())
    return out


class _RewriteAdapterBase:
    """Shared shell of the quantized serving adapters: mirror the
    cached-decode interface facts, register the memwatch census, and
    delegate the traced bodies under the adapter's graph pass scope
    (``self._pass`` — a ``passes/builtin`` quant pass whose scope is
    the ``runtime.quant_scope`` mapping activation).  Subclasses build
    ``self._inner``, ``self._entries``/``self._by_path`` and
    ``self._pass``, then call ``_init_common``."""

    def _init_common(self, inner):
        from .. import memwatch

        self.uses_pages = inner.uses_pages
        self.num_layers = inner.num_layers
        self.num_heads = inner.num_heads
        self.head_dim = inner.head_dim
        self.prefill_names = inner.prefill_names
        # the engine builds its pass pipeline from this
        # (passes.pipeline_for_serving reads adapter.passes)
        self.passes = (self._pass,)
        memwatch.register("quantized", self, _quantized_arrays)

    @staticmethod
    def _resolve_model(inner, who: str):
        model = getattr(inner, "model", None)
        if model is None:
            raise MXNetError(
                f"{who}: the wrapped adapter exposes no .model to "
                "quantize (FullPrefixAdapter-style logits functions own "
                "no layer tree — quantize the underlying block and wrap "
                "that)")
        return model

    # -- identity ------------------------------------------------------
    @property
    def model(self):
        return self._inner.model

    def quant_signature(self) -> Tuple:
        """Structural identity of the quantization config — the pass's
        signature.  A restart under different MX_QUANTIZE/MX_SERVE_INT4/
        MX_QUANT_* settings (or requantized weights) produces a
        different signature — the AOT cache then misses instead of
        loading the wrong program."""
        return self._pass.signature()

    def signature(self):
        return tuple(self._inner.signature()) + self.quant_signature()

    # -- params accounting (the bench's params-bytes story) ------------
    def quantized_param_bytes(self) -> int:
        """Bytes of the weights as the quantized graph holds them:
        packed/int8 for the rewritten layers' weights, original dtype
        for everything else (biases, norms, embeddings, excluded
        layers).  This is the PROGRAM's weight footprint
        (docs/PRECISION.md §Params-bytes accounting), not process
        residency — while the fp32 source net is alive the process
        holds both it and the quantized twins."""
        rewritten = {id(layer.weight)
                     for _path, layer in collect_quantizable(self.model)
                     if id(layer) in self._entries}
        total = sum(e.nbytes for e in self._entries.values())
        for p in self.model.collect_params().values():
            if id(p) not in rewritten:
                total += int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
        return total

    def fp32_param_bytes(self) -> int:
        return sum(int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
                   for p in self.model.collect_params().values())

    def quantized_weight_bytes(self) -> int:
        """Bytes of JUST the rewritten layers' weights as the quantized
        graph holds them (packed nibbles + scales for int4; int8 + range
        scalars' weight part for int8).  The per-layer compression
        acceptance ratio — whole-model ``quantized_param_bytes`` is
        diluted by f32 embeddings/norms that no weight rewrite touches."""
        return sum(e.nbytes for e in self._entries.values())

    def fp32_weight_bytes(self) -> int:
        """Original bytes of just the rewritten layers' weights."""
        return sum(e.orig_nbytes for e in self._entries.values())

    # -- delegated interface -------------------------------------------
    def extra_state(self, slots, ctx, dtype):
        return self._inner.extra_state(slots, ctx, dtype)

    def prefill_src(self, request):
        return self._inner.prefill_src(request)

    def prefill(self, F, src):
        with self._pass.scope():
            return self._inner.prefill(F, src)

    def install(self, state, slot, request):
        return self._inner.install(state, slot, request)

    def validate(self, request):
        return self._inner.validate(request)

    def max_positions(self):
        return self._inner.max_positions()

    def warmup(self, ctx):
        # eager f32 warmup: shape inference only — the quantized graph
        # appears at trace time, under the scope in decode/prefill
        return self._inner.warmup(ctx)

    def decode(self, F, tok, pos, table, keep, pages, rows, lengths,
               extra, pools):
        with self._pass.scope():
            return self._inner.decode(F, tok, pos, table, keep, pages,
                                      rows, lengths, extra, pools)

    def decode_logits(self, F, tok, pos, table, keep, pages, rows,
                      lengths, extra, pools):
        with self._pass.scope():
            return self._inner.decode_logits(F, tok, pos, table, keep,
                                             pages, rows, lengths, extra,
                                             pools)

    def advance_extra(self, F, extra, nxt, pos):
        with self._pass.scope():
            return self._inner.advance_extra(F, extra, nxt, pos)


class QuantizedAdapter(_RewriteAdapterBase):
    """Int8 twin of any :class:`~mxnet_tpu.serving.engine.ServingAdapter`.

    Same cached-decode interface; ``decode``/``prefill`` run the wrapped
    adapter's traced bodies under the ``quant_int8`` pass's scope
    (:func:`runtime.quant_scope`), so the selected Dense/Conv layers
    lower onto the int8 primitives inside the engine's ONE compiled
    executable.  Construct via :func:`quantize_adapter` (calibrated) —
    this constructor takes pre-computed thresholds."""

    precision = "int8"

    def __init__(self, inner, thresholds: Dict[str, Optional[float]],
                 calib_mode: str = "naive",
                 exclude: Iterable[str] = ()):
        from ..gluon import nn as gnn
        from ..passes.builtin import QuantizeInt8Pass

        cq = _calib_tools()
        model = self._resolve_model(inner, "QuantizedAdapter")
        self._inner = inner
        self._calib_mode = calib_mode
        self._entries: Dict[int, object] = {}
        self._by_path: Dict[str, object] = {}
        for path, layer in collect_quantizable(model, exclude):
            if path not in thresholds:
                raise MXNetError(
                    f"QuantizedAdapter: no calibration threshold for "
                    f"layer {path!r} (calibrate observed a different "
                    f"layer set?)")
            impl_cls = (cq.QuantizedConv2D if isinstance(layer, gnn.Conv2D)
                        else cq.QuantizedDense)
            twin = _TracedTwin(impl_cls(layer, thresholds[path]),
                               path, thresholds[path])
            self._entries[id(layer)] = twin
            self._by_path[path] = twin
        if not self._entries:
            raise MXNetError(
                "QuantizedAdapter: no quantizable Dense/Conv2D layers "
                "found in the wrapped adapter's model")
        per_layer = tuple(sorted(
            (path, round(e._w_thresh, 8),
             round(e.act_thresh, 8) if e.act_thresh is not None else None)
            for path, e in self._by_path.items()))
        self._pass = QuantizeInt8Pass(self._entries, calib_mode, per_layer)
        self._init_common(inner)


class Int4WeightAdapter(_RewriteAdapterBase):
    """Weight-only int4 twin of a ServingAdapter: every selected
    Dense/Conv weight is packed 2-per-byte with group-wise f16 scales
    and dequantized IN-TRACE (``_contrib_dequantize_int4``) inside the
    engine's compiled decode/prefill bodies — ~0.14x weight bytes at the
    default group of 32, no calibration (activations stay f32).
    Construct via :func:`int4_adapter` / :func:`maybe_int4_adapter`."""

    precision = "int4"

    def __init__(self, inner, group_size: int = 32,
                 exclude: Iterable[str] = ()):
        from ..gluon import nn as gnn
        from ..passes.builtin import QuantizeInt4Pass

        cq = _calib_tools()
        model = self._resolve_model(inner, "Int4WeightAdapter")
        self._inner = inner
        self._group_size = int(group_size)
        self._entries: Dict[int, object] = {}
        self._by_path: Dict[str, object] = {}
        for path, layer in collect_quantizable(model, exclude):
            impl = (cq.Int4Conv2D(layer, self._group_size)
                    if isinstance(layer, gnn.Conv2D)
                    else cq.Int4Dense(layer, self._group_size))
            twin = _Int4Twin(impl, path)
            self._entries[id(layer)] = twin
            self._by_path[path] = twin
        if not self._entries:
            raise MXNetError(
                "Int4WeightAdapter: no quantizable Dense/Conv2D layers "
                "found in the wrapped adapter's model")
        per_layer = tuple(sorted(
            (path, e.digest) for path, e in self._by_path.items()))
        self._pass = QuantizeInt4Pass(self._entries, self._group_size,
                                      per_layer)
        self._init_common(inner)


def quantize_adapter(adapter, calib_data, calib_fn: Callable,
                     calib_mode: str = "naive",
                     exclude: Iterable[str] = (),
                     num_calib_batches: Optional[int] = None
                     ) -> QuantizedAdapter:
    """Calibrate + wrap: the one-call driver producing an int8 serving
    adapter.  ``calib_fn(batch)`` runs one representative eager forward
    per calibration batch (a greedy ``translate`` over prompts is the
    natural choice for seq2seq serving)."""
    model = getattr(adapter, "model", None)
    if model is None:
        raise MXNetError("quantize_adapter: adapter exposes no .model")
    layers = collect_quantizable(model, exclude)
    if not layers:
        raise MXNetError("quantize_adapter: no quantizable Dense/Conv2D "
                         "layers in the adapter's model")
    thresholds = calibrate(layers, calib_data, calib_fn,
                           calib_mode=calib_mode,
                           num_calib_batches=num_calib_batches,
                           root=model)
    return QuantizedAdapter(adapter, thresholds, calib_mode=calib_mode,
                            exclude=exclude)


def maybe_quantize_adapter(adapter, calib_data=None, calib_fn=None,
                           exclude: Iterable[str] = ()):
    """The env-driven gate: ``MX_QUANTIZE=int8`` (or ``1``) quantizes
    ``adapter`` with the ``MX_QUANT_CALIB`` mode (default naive); unset/
    ``0`` returns the adapter untouched.  Calibration data is required
    when quantization is on — serving an uncalibrated int8 engine by
    accident must fail loudly, not degrade silently."""
    raw = (os.environ.get("MX_QUANTIZE") or "").strip().lower()
    if raw in ("", "0", "false", "off"):
        return adapter
    if raw not in ("1", "int8", "true", "on"):
        raise MXNetError(f"MX_QUANTIZE={raw!r}: expected int8/1 or 0/off")
    mode = (os.environ.get("MX_QUANT_CALIB") or "naive").strip().lower()
    if calib_data is None or calib_fn is None:
        raise MXNetError(
            "MX_QUANTIZE=int8 needs calibration data: pass calib_data + "
            "calib_fn to maybe_quantize_adapter (post-training int8 "
            "without calibrated ranges would quantize on the fly per "
            "step — run quantize_adapter explicitly if that is intended)")
    return quantize_adapter(adapter, calib_data, calib_fn, calib_mode=mode,
                            exclude=exclude)


def int4_adapter(adapter, group_size: int = 32,
                 exclude: Iterable[str] = ()) -> Int4WeightAdapter:
    """Wrap ``adapter`` for weight-only int4 serving.  No calibration
    step — packing is a pure function of the weights (group-wise
    symmetric, ``contrib.quantization._quantize_weight_int4_np``)."""
    return Int4WeightAdapter(adapter, group_size=group_size,
                             exclude=exclude)


def maybe_int4_adapter(adapter, exclude: Iterable[str] = ()):
    """The env-driven gate: ``MX_SERVE_INT4=1`` (or ``int4``) wraps
    ``adapter`` for weight-only int4 serving with the ``MX_QUANT_GROUP``
    group size (default 32); unset/``0`` returns the adapter untouched.
    Composing with ``MX_QUANTIZE=int8`` is rejected — the two rewrites
    claim the same Dense/Conv layers."""
    raw = (os.environ.get("MX_SERVE_INT4") or "").strip().lower()
    if raw in ("", "0", "false", "off"):
        return adapter
    if raw not in ("1", "int4", "true", "on"):
        raise MXNetError(f"MX_SERVE_INT4={raw!r}: expected int4/1 or 0/off")
    if (os.environ.get("MX_QUANTIZE") or "").strip().lower() not in \
            ("", "0", "false", "off"):
        raise MXNetError(
            "MX_SERVE_INT4 and MX_QUANTIZE are both set: the int4 and "
            "int8 rewrites claim the same Dense/Conv layers — pick one")
    graw = (os.environ.get("MX_QUANT_GROUP") or "32").strip()
    try:
        group = int(graw)
    except ValueError:
        raise MXNetError(f"MX_QUANT_GROUP={graw!r}: expected an even int")
    return int4_adapter(adapter, group_size=group, exclude=exclude)
