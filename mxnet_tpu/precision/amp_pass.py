"""The graph-level AMP pass: apply a cast policy to a traced block apply.

Where ``contrib/amp`` casts PARAMETERS eagerly (``block.cast('bfloat16')``
— every op then runs in bf16, including the ones that shouldn't), this
pass rewrites the PROGRAM: during the one trace ``DataParallelStep._build``
runs, every op dispatch consults the active
:class:`~mxnet_tpu.precision.config.AmpPolicy` (see
``runtime.cast_inputs``, hooked into ``ops/registry.py``):

  * ``low``-class ops (matmul/conv) trace with their f32 float inputs
    cast to the policy dtype — parameters stay f32 master copies, the
    cast is a graph edge XLA fuses into the producer;
  * ``widen``-class ops (softmax/norm/reductions) trace with any
    low-precision float inputs cast back to f32;
  * block outputs cast to f32 at the boundary, so the loss (and its
    gradient seed) is always computed in f32.

Because the policy is applied at trace time inside ``_build``, the whole
mixed-precision program lands in ONE compiled executable — it composes
with superstep ``lax.scan`` (the scan body is the same traced step), the
AOT executable cache (the policy signature joins ``_fingerprint_parts``)
and the ``Plan`` (``Plan.precision`` serializes it into checkpoint
layouts).  With no policy the wrapped apply is returned UNCHANGED — the
AMP-off program is byte-for-byte the pre-pass program.
"""
from __future__ import annotations

from .config import AmpPolicy, LossScaleConfig, PrecisionConfig
from .runtime import amp_scope

__all__ = ["apply_amp", "amp_scope", "AmpPolicy", "LossScaleConfig",
           "PrecisionConfig"]


def apply_amp(apply_fn, policy: AmpPolicy):
    """Wrap a ``fn(params, key, *inputs) -> (out_or_list, aux)`` block
    apply so its trace runs under ``policy``, with f32 outputs at the
    boundary.  Identity when ``policy`` is None."""
    if policy is None:
        return apply_fn

    def amp_apply(params, key, *inputs):
        import jax.numpy as jnp

        def widen(arr):
            return (arr.astype(jnp.float32)
                    if jnp.issubdtype(arr.dtype, jnp.floating)
                    and arr.dtype != jnp.float32 else arr)

        with amp_scope(policy):
            out, aux = apply_fn(params, key, *inputs)
        if isinstance(out, list):
            out = [widen(o) for o in out]
        else:
            out = widen(out)
        return out, aux

    return amp_apply
