"""Serializable precision configuration: the AMP cast policy, the dynamic
loss-scale hyperparameters, and the :class:`PrecisionConfig` pair that
rides on a :class:`~mxnet_tpu.parallel.plan.Plan`.

This module is deliberately dependency-free (base + dataclasses only):
``parallel/plan.py`` imports it at module level, and the op registry's
dispatch hook reads the active policy on every op call — neither may pull
in jax, gluon, or numpy at import time.

The policy model is the TF/TVM graph-pass one (arXiv:1802.04799), not the
per-call wrapper of ``contrib/amp``: op CLASSES get dispositions —

  * ``low``   — compute in the target dtype (matmul/conv families: the
    MXU-bound ops where bf16 halves HBM traffic and doubles MXU issue
    rate; accumulation stays f32 via the ops' safe-accumulation rules);
  * ``widen`` — force f32 (softmax/norm/reduction families: the ops whose
    bf16 error compounds);
  * anything else passes through in whatever dtype arrives (elementwise
    ops are precision-neutral; jnp promotion widens mixed operands).

Env surface (registered in env_vars.py): ``MX_AMP`` turns the pass on
(``bf16``/``bfloat16``/``1`` or ``fp16``/``float16``), ``MX_AMP_POLICY``
overrides the op lists as inline JSON, ``MX_LOSS_SCALE`` configures the
traced dynamic loss scaler (``dynamic``, a fixed float, or ``0`` to
disable; fp16 defaults it on, bf16 off — bf16 shares f32's exponent
range).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..base import MXNetError

__all__ = ["AmpPolicy", "LossScaleConfig", "PrecisionConfig",
           "DEFAULT_LOW_OPS", "DEFAULT_WIDEN_OPS"]

# matmul/conv compute classes: bf16 inputs, f32 accumulation (the ops'
# _safe_acc / native-MXU rules — see ops/nn.py)
DEFAULT_LOW_OPS: Tuple[str, ...] = (
    "FullyConnected", "Convolution", "Deconvolution",
    "dot", "batch_dot", "_contrib_flash_attention",
)

# numerically-sensitive classes: f32 inputs regardless of what arrives
# (reductions/softmax/norms — the reference AMP's FP32_FUNCS analog)
DEFAULT_WIDEN_OPS: Tuple[str, ...] = (
    "softmax", "log_softmax", "SoftmaxActivation", "SoftmaxOutput",
    "softmax_cross_entropy", "BatchNorm", "LayerNorm", "InstanceNorm",
    "GroupNorm", "L2Normalization", "norm", "sum", "sum_axis", "mean",
    "logsumexp", "exp", "log",
)

_AMP_DTYPES = ("bfloat16", "float16")


@dataclass(frozen=True)
class AmpPolicy:
    """Per-op-class cast policy of the graph-level AMP pass."""

    dtype: str = "bfloat16"
    low: Tuple[str, ...] = DEFAULT_LOW_OPS
    widen: Tuple[str, ...] = DEFAULT_WIDEN_OPS

    def __post_init__(self):
        if self.dtype not in _AMP_DTYPES:
            raise MXNetError(
                f"AmpPolicy: dtype must be one of {_AMP_DTYPES}, got "
                f"{self.dtype!r}")
        object.__setattr__(self, "low", tuple(self.low))
        object.__setattr__(self, "widen", tuple(self.widen))
        both = set(self.low) & set(self.widen)
        if both:
            raise MXNetError(
                f"AmpPolicy: ops {sorted(both)} appear in both the low and "
                f"widen lists — a policy must give each op ONE disposition")

    def op_class(self, op_name: str) -> Optional[str]:
        """'low' / 'widen' / None for one registered op name (the
        registry dispatch hook's single lookup)."""
        if op_name in self.low:
            return "low"
        if op_name in self.widen:
            return "widen"
        return None

    def signature(self) -> Tuple:
        """Hashable structural identity (executable fingerprints)."""
        return ("amp", self.dtype, self.low, self.widen)

    def to_json(self) -> dict:
        return {"dtype": self.dtype, "low": list(self.low),
                "widen": list(self.widen)}

    @classmethod
    def from_json(cls, rec: dict) -> "AmpPolicy":
        return cls(dtype=rec.get("dtype", "bfloat16"),
                   low=tuple(rec.get("low", DEFAULT_LOW_OPS)),
                   widen=tuple(rec.get("widen", DEFAULT_WIDEN_OPS)))


@dataclass(frozen=True)
class LossScaleConfig:
    """Traced dynamic loss scaling (docs/PRECISION.md §Loss-scale state
    machine).  All state transitions run INSIDE the compiled step as
    device values; these hyperparameters are trace constants and key the
    executable fingerprint.  ``dynamic=False`` pins ``init_scale``
    forever (a static scale; skip-step protection still applies)."""

    init_scale: float = 2.0 ** 15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 200
    dynamic: bool = True

    def __post_init__(self):
        if self.init_scale <= 0:
            raise MXNetError("LossScaleConfig: init_scale must be > 0")
        if self.growth_factor <= 1.0:
            raise MXNetError("LossScaleConfig: growth_factor must be > 1")
        if not (0.0 < self.backoff_factor < 1.0):
            raise MXNetError(
                "LossScaleConfig: backoff_factor must be in (0, 1)")
        if self.growth_interval < 1:
            raise MXNetError(
                "LossScaleConfig: growth_interval must be >= 1")

    def signature(self) -> Tuple:
        return ("loss_scale", self.init_scale, self.growth_factor,
                self.backoff_factor, self.growth_interval, self.dynamic)

    def to_json(self) -> dict:
        return {"init_scale": self.init_scale,
                "growth_factor": self.growth_factor,
                "backoff_factor": self.backoff_factor,
                "growth_interval": self.growth_interval,
                "dynamic": self.dynamic}

    @classmethod
    def from_json(cls, rec: dict) -> "LossScaleConfig":
        return cls(init_scale=float(rec.get("init_scale", 2.0 ** 15)),
                   growth_factor=float(rec.get("growth_factor", 2.0)),
                   backoff_factor=float(rec.get("backoff_factor", 0.5)),
                   growth_interval=int(rec.get("growth_interval", 200)),
                   dynamic=bool(rec.get("dynamic", True)))


@dataclass(frozen=True)
class PrecisionConfig:
    """What a Plan carries about precision: the AMP policy (or None for
    full f32) and the loss-scale config (or None for unscaled)."""

    amp: Optional[AmpPolicy] = None
    loss_scale: Optional[LossScaleConfig] = None

    def signature(self) -> Tuple:
        return ("precision",
                self.amp.signature() if self.amp is not None else None,
                self.loss_scale.signature()
                if self.loss_scale is not None else None)

    def to_json(self) -> dict:
        return {
            "amp": self.amp.to_json() if self.amp is not None else None,
            "loss_scale": (self.loss_scale.to_json()
                           if self.loss_scale is not None else None),
        }

    @classmethod
    def from_json(cls, rec: Optional[dict]) -> Optional["PrecisionConfig"]:
        if rec is None:
            return None
        amp = rec.get("amp")
        ls = rec.get("loss_scale")
        return cls(amp=AmpPolicy.from_json(amp) if amp else None,
                   loss_scale=(LossScaleConfig.from_json(ls)
                               if ls else None))

    # -- env surface ---------------------------------------------------
    @classmethod
    def from_env(cls, environ=None) -> Optional["PrecisionConfig"]:
        """MX_AMP / MX_AMP_POLICY / MX_LOSS_SCALE -> a PrecisionConfig,
        or None when MX_AMP is unset/off.  Read ONCE at step
        construction (the policy is executable identity — re-reading per
        step would let an env flip silently split the program from its
        fingerprint)."""
        environ = environ if environ is not None else os.environ
        raw = (environ.get("MX_AMP") or "").strip().lower()
        if raw in ("", "0", "false", "off"):
            return None
        if raw in ("1", "true", "on", "bf16", "bfloat16"):
            dtype = "bfloat16"
        elif raw in ("fp16", "float16"):
            dtype = "float16"
        else:
            raise MXNetError(
                f"MX_AMP={raw!r}: expected bf16/bfloat16/1 or fp16/float16 "
                f"(or 0/off)")
        pol_raw = (environ.get("MX_AMP_POLICY") or "").strip()
        if pol_raw:
            try:
                rec = json.loads(pol_raw)
            except ValueError as e:
                raise MXNetError(
                    f"MX_AMP_POLICY is not valid JSON ({e}); expected "
                    '{"low": [...], "widen": [...], "dtype": ...}')
            rec.setdefault("dtype", dtype)
            amp = AmpPolicy.from_json(rec)
        else:
            amp = AmpPolicy(dtype=dtype)
        ls_raw = (environ.get("MX_LOSS_SCALE") or "").strip().lower()
        if ls_raw in ("0", "false", "off", "none"):
            ls = None
        elif ls_raw in ("", "auto"):
            # fp16's 5-bit exponent underflows small grads without
            # scaling; bf16 shares f32's exponent range and needs none
            ls = LossScaleConfig() if dtype == "float16" else None
        elif ls_raw in ("1", "dynamic", "true", "on"):
            ls = LossScaleConfig()
        else:
            try:
                ls = LossScaleConfig(init_scale=float(ls_raw),
                                     dynamic=False)
            except ValueError:
                raise MXNetError(
                    f"MX_LOSS_SCALE={ls_raw!r}: expected 'dynamic', a "
                    f"fixed scale float, or 0/off") from None
        return cls(amp=amp, loss_scale=ls)
