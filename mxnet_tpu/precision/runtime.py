"""Trace-time precision scopes: the one global the hot dispatch paths
consult.

Two scopes live here because their consumers sit on the hottest import
paths of the package (``ops/registry.py`` dispatch and the gluon
Dense/Conv forward) and must pay ONE module-global read when precision is
off:

  * :func:`amp_scope` — while active, :func:`cast_inputs` applies the
    graph-level AMP cast policy at the op-dispatch point: ``low``-class
    ops get f32 float inputs cast down to the policy dtype, ``widen``-
    class ops get low-precision float inputs cast back up to f32.
    Activated by ``DataParallelStep._build`` around the traced block
    apply, so the casts are traced INTO the one compiled step program —
    never per-op eager work.
  * :func:`quant_scope` — while active, :func:`quant_entry` resolves a
    Dense/Conv layer to its calibrated int8 twin
    (``precision/quantize.py``); the layer's ``hybrid_forward`` then
    routes through the int8 kernels inside the engine's traced
    decode/prefill graphs.

Scopes nest and restore (context managers); they are trace-time state,
set around a jit trace or an eager region by exactly one thread — the
same discipline as ``gluon.parameter.begin_trace``.
"""
from __future__ import annotations

import contextlib

from ..passes import hooks as _hooks

__all__ = ["amp_scope", "amp_active", "cast_inputs", "quant_scope",
           "quant_entry"]

_AMP_POLICY = None   # active AmpPolicy, or None (the fast-path check)
_QUANT_MAP = None    # active {id(layer): quantized-twin}, or None


class _AmpHook(_hooks.OpHook):
    """The AMP pass's dispatch hook: per-op-class input casts.  Since
    the pass pipeline, ``ops/registry._invoke_impl`` consults the ONE
    hook tuple instead of this module's global directly — the cast logic
    itself is unchanged (``cast_inputs`` below)."""

    def rewrite_inputs(self, op_name, inputs):
        return cast_inputs(op_name, inputs)


_AMP_HOOK = _AmpHook()


def amp_active() -> bool:
    return _AMP_POLICY is not None


@contextlib.contextmanager
def amp_scope(policy):
    """Activate ``policy`` (an :class:`~mxnet_tpu.precision.config.
    AmpPolicy`) for the ops dispatched inside the block."""
    global _AMP_POLICY
    prev = _AMP_POLICY
    _AMP_POLICY = policy
    try:
        with _hooks.op_hook(_AMP_HOOK):
            yield
    finally:
        _AMP_POLICY = prev


def cast_inputs(op_name: str, inputs):
    """Apply the active cast policy to one op call's NDArray inputs.

    Reached from ``ops.registry._invoke_impl`` via the pass-pipeline
    hook (``passes/hooks.py``) ONLY while an amp_scope is active — the
    hook tuple is empty otherwise, so the AMP-off dispatch path is
    byte-for-byte unchanged.  Casts are real ops and
    inline into whatever trace is running — that is the graph-level
    pass: the cast decisions are properties of the traced program, not
    of eager per-call wrappers."""
    policy = _AMP_POLICY
    cls = policy.op_class(op_name)
    if cls is None:
        return inputs
    import numpy as np

    low = np.dtype(policy.dtype)
    f32 = np.dtype(np.float32)
    if cls == "low":
        src, dst = f32, policy.dtype
    else:  # widen
        src, dst = low, "float32"
    out = list(inputs)
    changed = False
    for i, x in enumerate(out):
        if np.dtype(x.dtype) == src:
            out[i] = x.astype(dst)
            changed = True
    return out if changed else inputs


@contextlib.contextmanager
def quant_scope(mapping):
    """Activate a {id(layer): int8-twin} mapping for the layers called
    inside the block (the serving adapter's traced decode/prefill)."""
    global _QUANT_MAP
    prev = _QUANT_MAP
    _QUANT_MAP = mapping
    try:
        yield
    finally:
        _QUANT_MAP = prev


def quant_entry(layer):
    """The active int8 twin for ``layer``, or None (the single check the
    gluon Dense/Conv forward pays; one global read when quantization is
    off)."""
    m = _QUANT_MAP
    if m is None:
        return None
    return m.get(id(layer))
