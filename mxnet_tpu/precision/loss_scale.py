"""Traced dynamic loss scaling: every transition of the scaler state
machine runs INSIDE the compiled train step as device values.

The eager reference (``contrib/amp/amp.py`` ``DynamicLossScaler``) reads
every gradient back to host per step to decide overflow — a per-step
device->host sync that would stall the PR 4 async pipeline and the PR 9
superstep scan.  Here the whole protocol is traced:

  * the loss is multiplied by the scale before ``value_and_grad`` (small
    fp16 grads then survive the 5-bit exponent);
  * un-scaling folds into the optimizer's existing ``rescale_grad``
    multiply (``rescale / scale`` — zero extra HBM passes);
  * overflow detection is one fused ``isfinite``-all reduce over the
    gradient tree;
  * a non-finite step SELECTS the old params/optimizer state (a traced
    no-op update: weights, momenta and Adam's ``t`` all hold), halves
    the scale, and resets the growth counter;
  * ``growth_interval`` consecutive finite steps double the scale.

The scaler state — ``scale`` (f32), ``growth`` (i32 consecutive-finite
counter), ``skipped`` (i32 cumulative skip count, observability) — is
part of the step's train state: it threads through the jitted step and
the superstep ``lax.scan`` carry, is checkpointed alongside the
optimizer slots (``amp.*`` keys in ``opt_state``), and survives elastic
reshard (replicated scalars place trivially on any mesh).

``overflow_flag`` is the eager-path export: ONE fused reduce over a
gradient list returning a DEVICE scalar, used by the
``contrib/amp`` compatibility shim so legacy Trainer scripts stop paying
a readback per gradient (they still pay exactly one, at the shim's
python-bool boundary).  It is registered in mxlint's HOT_PATH_ENTRIES —
no host sync may ever enter it.
"""
from __future__ import annotations

from typing import Dict

from .config import LossScaleConfig

__all__ = ["init_scaler_host", "grads_finite", "scaler_update",
           "overflow_flag", "SCALER_KEYS"]

# checkpoint key order (state_dict writes `amp.<key>` opt_state entries)
SCALER_KEYS = ("scale", "growth", "skipped")


def init_scaler_host(cfg: LossScaleConfig) -> Dict[str, "object"]:
    """Fresh host-side scaler state (the caller places it on device with
    its own sharding rules — replicated scalars)."""
    import numpy as np

    return {"scale": np.float32(cfg.init_scale),
            "growth": np.int32(0),
            "skipped": np.int32(0)}


def _all_finite(arrays):
    """Traced AND-of-isfinite fold over device arrays — the one shared
    reduction both the compiled step (``grads_finite``) and the eager
    shim (``overflow_flag``) build on, so their overflow semantics can
    never drift."""
    import jax.numpy as jnp

    flags = [jnp.all(jnp.isfinite(a)) for a in arrays]
    if not flags:
        return jnp.asarray(True)
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


def grads_finite(grads: Dict[str, "object"], mults: Dict[str, tuple]):
    """Traced all-finite flag over the TRAINABLE gradients (frozen
    params — lr_mult None in ``mults`` — are excluded; their grads never
    feed an update)."""
    return _all_finite([g for name, g in grads.items()
                        if mults.get(name, (1.0, 1.0))[0] is not None])


def scaler_update(state: Dict[str, "object"], finite,
                  cfg: LossScaleConfig) -> Dict[str, "object"]:
    """One traced transition of the scaler state machine.

    finite: overflow -> scale *= backoff (floored at 1.0), growth
    counter resets, skip counter bumps.  ``growth_interval`` consecutive
    finite steps -> scale *= growth_factor, counter resets.  With
    ``dynamic=False`` the scale is pinned; only the skip counter moves.
    """
    import jax.numpy as jnp

    scale = state["scale"]
    growth = state["growth"]
    skipped = state["skipped"] + jnp.where(finite, 0, 1).astype(jnp.int32)
    if not cfg.dynamic:
        return {"scale": scale, "growth": growth, "skipped": skipped}
    grown = (growth + 1) >= cfg.growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grown, scale * cfg.growth_factor, scale),
        jnp.maximum(scale * cfg.backoff_factor, 1.0)).astype(jnp.float32)
    new_growth = jnp.where(jnp.logical_and(finite, jnp.logical_not(grown)),
                           growth + 1, 0).astype(jnp.int32)
    return {"scale": new_scale, "growth": new_growth, "skipped": skipped}


_OVERFLOW_JIT = None


def _overflow_impl(arrays):
    import jax.numpy as jnp

    return jnp.logical_not(_all_finite(arrays))


def overflow_flag(arrays):
    """ONE fused any-non-finite reduce over a list of device arrays ->
    a DEVICE 0-d bool (True = overflow).  The eager shim's building
    block: dispatch here is async; the caller decides when (whether) to
    read the flag back."""
    global _OVERFLOW_JIT
    if _OVERFLOW_JIT is None:
        import jax

        # mxlint: disable=retrace-hazard — built once, module-cached;
        # jax's own dispatch cache keys the per-signature specializations
        _OVERFLOW_JIT = jax.jit(_overflow_impl)
    return _OVERFLOW_JIT(tuple(arrays))
