"""Training monitor (reference: python/mxnet/monitor.py Monitor ~L1-150):
periodic statistics over watched arrays for debugging divergence/NaNs.

TPU-native scope: the reference registers a per-op output callback inside
the engine; here whole graphs are single XLA executables, so intermediate
op outputs are fused away.  The monitor therefore watches the executor's
OBSERVABLE arrays — arguments (params), gradients, aux states and outputs
— which is where NaN/explosion debugging lands in practice; per-op
visibility is available by running eager (MXNET_ENGINE_TYPE=NaiveEngine)
or via mx.profiler.
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

__all__ = ["Monitor"]


class Monitor:
    """Watch arrays matching `pattern` every `interval` batches."""

    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        self.interval = max(1, int(interval))
        if stat_func is None:
            def stat_func(arr):
                import numpy as np

                # reference default: norm(x)/sqrt(x.size) i.e. RMS
                # (python/mxnet/monitor.py asum_stat)
                a = np.asarray(arr, dtype=np.float64)
                return float(np.sqrt(np.mean(np.square(a))))
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.exes: List = []
        self.queue: List[Tuple[int, str, str]] = []
        self.logger = logging.getLogger("mxnet_tpu.monitor")

    def install(self, exe) -> None:
        """Watch an Executor's arg/grad/aux/output arrays (idempotent;
        a repeated fit() re-installs without duplicating)."""
        if not any(e is exe for e in self.exes):
            self.exes.append(exe)

    def replace(self, old_exe, new_exe) -> None:
        """Swap a rebound module's executor (force_rebind) so stats never
        come from the abandoned executor's frozen arrays."""
        self.exes = [e for e in self.exes if e is not old_exe]
        self.install(new_exe)

    # ------------------------------------------------------------------
    def tic(self) -> None:
        """Start collection for this batch when the interval hits."""
        self.activated = (self.step % self.interval == 0)
        self.step += 1

    def _collect(self, name, nd_arr):
        if not self.re_pattern.match(name):
            return
        import numpy as np

        arr = np.asarray(nd_arr.asnumpy())
        try:
            stat = self.stat_func(arr)
        except Exception as exc:  # a bad stat fn shouldn't kill training
            stat = f"<stat error: {exc}>"
        self.queue.append((self.step - 1, name, str(stat)))

    def toc(self) -> List[Tuple[int, str, str]]:
        """Collect stats from installed executors; returns (step, name,
        stat) triples and clears the queue."""
        if not self.activated:
            return []
        for exe in self.exes:
            # only executors that ran since the last toc (bucketing: the
            # inactive buckets' outputs are stale and their shared params
            # would be reported twice).  Executors outside a fit loop
            # default to "ran" so manual tic/forward/toc works.
            if not getattr(exe, "_monitor_ran", True):
                continue
            exe._monitor_ran = False
            for name, arr in getattr(exe, "arg_dict", {}).items():
                self._collect(name, arr)
            for name, arr in (getattr(exe, "grad_dict", {}) or {}).items():
                if arr is not None:
                    self._collect(name + "_grad", arr)
            for name, arr in getattr(exe, "aux_dict", {}).items():
                self._collect(name, arr)
            for i, out in enumerate(getattr(exe, "outputs", []) or []):
                self._collect(f"output{i}", out)
        self.activated = False
        res = self.queue
        if self.sort:
            res = sorted(res, key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self) -> None:
        for step, name, stat in self.toc():
            self.logger.info("Batch: %7d %30s %s", step, name, stat)
