"""mxnet_tpu: a TPU-native deep-learning framework with the MXNet 1.x API.

A ground-up rebuild of the capabilities of ROCmSoftwarePlatform/mxnet
(Apache MXNet 1.x, HIP/ROCm fork) designed for TPU hardware: NDArray storage
backs onto XLA/PjRt device buffers, operators lower to XLA HLO (with Pallas
kernels for hot fused ops), hybridized Gluon blocks JIT-compile into single
XLA computations, and KVStore('device') rides ICI collectives instead of
NCCL/RCCL.  See SURVEY.md for the component-by-component mapping.

Usage mirrors the reference::

    import mxnet_tpu as mx           # or: import mxnet as mx (shim package)
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
__version__ = "0.1.0"

from .base import MXNetError
from .context import (Context, cpu, gpu, tpu, cpu_pinned, current_context,
                      num_gpus, num_tpus)
from . import engine
from . import random
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import initializer
from . import initializer as init
from . import optimizer
from .optimizer import lr_scheduler
from . import metric
from . import kvstore
from . import kvstore as kv  # reference alias: mx.kv.create(...)
from .kvstore import KVStore
from . import recordio
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import module
from . import module as mod
from . import gluon
from . import parallel
from . import precision
from . import passes
from . import io
from . import image
from . import callback
from . import model
from . import operator
from . import rnn
from . import monitor
from . import name
from . import attribute
from .attribute import AttrScope
from .monitor import Monitor
from . import profiler
from . import telemetry
from . import memwatch
from . import metrics_server
from . import runtime
from . import util
from .util import is_np_array
from . import env_vars
from . import subgraph
from . import visualization
from . import visualization as viz
from . import checkpoint
from . import fault
from . import rtc
from . import test_utils
from . import contrib
from . import models

# Multi-process rendezvous must run BEFORE any computation initializes the
# jax backends, so when the launcher env (tools/launch.py: MX_COORDINATOR /
# DMLC_PS_ROOT_URI) is present, connect at import time (reference analog:
# ps::Postoffice::Start, which launch.py's env likewise triggers).
parallel.dist.init_from_env()

# surface set-but-ineffective MXNET_* env vars in logs (env_vars.describe()
# has the full disposition table)
env_vars.check()

# live metrics endpoint (docs/OBSERVABILITY.md §Live metrics): serves
# /metrics /healthz /statusz when MX_METRICS_PORT enables it — after the
# rendezvous above so telemetry.rank() (the port offset + portfile name)
# reflects this process's gang rank
metrics_server.maybe_start()


def waitall():
    engine.wait_all()
