"""Network visualization (reference: python/mxnet/visualization.py —
print_summary, plot_network over graphviz).

print_summary walks the symbol graph printing a layer table with output
shapes and parameter counts; plot_network emits a graphviz Digraph (gated
on the optional graphviz package).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _param_count(node, shapes: Dict[str, tuple], input_names) -> int:
    """Trainable-parameter count: variable inputs that are neither
    network INPUTS (anything the user listed in `shape` — data, rois,
    im_info, ...) nor aux/label state."""
    total = 0
    for parent, _ in node.inputs:
        if parent.is_variable() and not parent.name.endswith(
                ("_moving_mean", "_moving_var", "label")):
            shp = shapes.get(parent.name)
            if shp and parent.name not in input_names:
                total += int(np.prod(shp))
    return total


def print_summary(symbol, shape: Optional[dict] = None, line_length: int = 98,
                  positions=(0.44, 0.64, 0.74, 1.0)) -> None:
    """Print a Keras-style layer summary (reference: print_summary ~L50).

    shape: dict of input name -> shape (e.g. {'data': (1, 3, 224, 224)}).
    """
    from .symbol.symbol import _topo_order

    shapes: Dict[str, tuple] = {}
    out_shapes: Dict[int, tuple] = {}
    input_names = set(shape) if shape is not None else {"data"}
    if shape is not None:
        arg_shapes, out_s, aux_shapes = symbol.infer_shape(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            shapes[name] = s
        internals = symbol.get_internals()
        # per-node output shapes via get_internals inference
        try:
            _, int_shapes, _ = internals.infer_shape(**shape)
            for entry, s in zip(internals._entries, int_shapes):
                out_shapes[id(entry[0])] = s
        except MXNetError:
            pass

    order = _topo_order(symbol._entries)
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    positions = [int(line_length * p) for p in positions]

    def print_row(values):
        line = ""
        for v, pos in zip(values, positions):
            line = (line + str(v))[: pos - 1]
            line += " " * (pos - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    total_params = 0
    for node in order:
        if node.is_variable():
            continue
        params = _param_count(node, shapes, input_names)
        total_params += params
        prev = ",".join(p.name for p, _ in node.inputs
                        if not p.is_variable())[:30]
        oshape = out_shapes.get(id(node), "")
        print_row([f"{node.name} ({node.op})", oshape, params, prev])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Return a graphviz.Digraph of the network (reference: plot_network).

    Requires the optional `graphviz` python package.
    """
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError(
            "plot_network requires the 'graphviz' package, which is not "
            "installed in this environment; use print_summary for a text "
            "rendering") from None
    from .symbol.symbol import _topo_order

    node_attrs = node_attrs or {}
    dot = Digraph(name=title, format=save_format)
    base_attr = {"shape": "box", "fixedsize": "false", "style": "filled"}
    base_attr.update(node_attrs)
    palette = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
               "BatchNorm": "#bebada", "Activation": "#ffffb3",
               "Pooling": "#80b1d3", "Concat": "#fdb462",
               "softmax": "#fccde5", "SoftmaxOutput": "#fccde5"}
    order = _topo_order(symbol._entries)
    drawn = set()
    for node in order:
        if node.is_variable():
            if hide_weights and node.name != "data":
                continue
            dot.node(node.name, node.name,
                     dict(base_attr, fillcolor="#8dd3c7"))
            drawn.add(id(node))
            continue
        color = palette.get(node.op, "#d9d9d9")
        label = f"{node.name}\n{node.op}"
        k = node.attrs.get("kernel")
        if k:
            label += f" {tuple(k)}"
        dot.node(node.name, label, dict(base_attr, fillcolor=color))
        drawn.add(id(node))
        for parent, _ in node.inputs:
            if id(parent) in drawn:
                dot.edge(parent.name, node.name)
    return dot
