"""Live per-rank metrics endpoint (docs/OBSERVABILITY.md §Live metrics).

The telemetry spine is otherwise post-mortem: ``export_prometheus``
writes a file snapshot at atexit and health is heartbeat *files* the
supervisor polls.  This module adds the pull-based plane a production
serving fleet scrapes: one stdlib-only (``http.server`` + daemon thread)
HTTP endpoint per rank, enabled via ``MX_METRICS_PORT``:

  unset / empty / ``off``   endpoint disabled (the default — nothing
                            binds, nothing to pay);
  ``0`` / ``auto``          bind an EPHEMERAL port and write it to a
                            portfile next to the heartbeat
                            (``metrics-port-<rank>.json`` under
                            ``MX_TELEMETRY_DIR``) so the tools/launch.py
                            supervisor discovers it for the gang merge;
  ``N`` (> 0)               bind ``N + rank`` — the rank offset keeps a
                            single-host gang from colliding on one port
                            (rank 0 gets exactly N).  The portfile is
                            still written when a telemetry dir exists.

Routes (all served from the telemetry recorder's LOCKED ROLLUPS only —
the handler never imports jax, never touches device state, never forces
a sync; enforced by mxlint's jax-free reachability check on this file):

  ``/metrics``   the current ``telemetry.summary()`` + ``memwatch``
                 rollups through the SAME OpenMetrics formatter the
                 atexit file export uses (``telemetry.render_prometheus``
                 — one formatter, two sinks), stamped
                 ``mx_export_mode{mode="live"}``;
  ``/healthz``   200/503 JSON verdict from heartbeat age (the
                 supervisor's staleness rule), last step, restart count
                 and in-flight depth (``telemetry.health_snapshot``);
  ``/statusz``   the summary JSON + memwatch summary + the
                 flight-recorder tail — the "what was this rank doing"
                 one-shot for humans and for the supervisor's
                 pre-teardown snapshot.  The serving block includes the
                 weight hot-swap generation/counters
                 (``summary()['serving']['weight_generation']`` —
                 docs/SERVING.md §Weight hot-swap);
  ``/tracez``    the last K completed serving requests (trace id,
                 attributed cause, latency, SLO verdicts) from the
                 recorder's bounded ring — the per-rank half of the
                 router's fleet-level ``/tracez``
                 (docs/OBSERVABILITY.md §Request tracing).

The server binds ``MX_METRICS_HOST`` (default ``127.0.0.1``; set
``0.0.0.0`` to expose it to a cross-host scraper) and runs on daemon
threads: it can never hold the process open, and a request can never
block the training/serving loop (shared state is only ever read under
the recorder's locks).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import telemetry

__all__ = ["enabled", "port", "start", "stop", "maybe_start",
           "portfile_path"]

_LOG = logging.getLogger("mxnet_tpu.metrics_server")

OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def portfile_path(directory: str, rank_id: int) -> str:
    """Per-rank portfile path (mirrored in tools/launch.py, which must
    stay importable without jax/mxnet_tpu — keep in sync)."""
    return os.path.join(directory, f"metrics-port-{rank_id}.json")


def _config_port() -> Optional[int]:
    """MX_METRICS_PORT -> base port (0 = ephemeral) or None (disabled)."""
    raw = os.environ.get("MX_METRICS_PORT", "").strip().lower()
    if not raw or raw in ("off", "false", "none"):
        return None
    if raw in ("0", "auto", "ephemeral"):
        return 0
    try:
        p = int(raw)
    except ValueError:
        p = -1  # non-integer garbage: same disabled-with-warning path
    if p <= 0:  # "0"/"auto" already matched above; negatives are invalid
        _LOG.warning("MX_METRICS_PORT=%r is not a port; metrics endpoint "
                     "disabled", raw)
        return None
    return p


class _Handler(BaseHTTPRequestHandler):
    """Route handler.  mxlint JAX_FREE_ENTRIES starts its reachability
    scan at ``_Handler.do_GET``: everything reachable from here must be
    rollup-only — no jax import, no host readback of device values."""

    server_version = "mxnet-tpu-metrics/1"

    def do_GET(self):  # noqa: N802 (http.server contract)
        route = self.path.split("?", 1)[0].rstrip("/") or "/"
        if route in ("/", "/metrics"):
            self._metrics()
        elif route == "/healthz":
            self._healthz()
        elif route == "/statusz":
            self._statusz()
        elif route == "/tracez":
            self._tracez()
        else:
            self._send(404, "text/plain; charset=utf-8",
                       f"no such route {route!r}; try /metrics /healthz "
                       "/statusz /tracez\n")

    def _metrics(self):
        self._send(200, OPENMETRICS_CONTENT_TYPE,
                   telemetry.render_prometheus(mode="live"))

    def _healthz(self):
        snap = telemetry.health_snapshot()
        self._send(200 if snap["healthy"] else 503,
                   "application/json", json.dumps(snap) + "\n")

    def _statusz(self):
        body = {
            "summary": telemetry.summary(),
            "flight": telemetry.flight_tail(32),
            "health": telemetry.health_snapshot(),
            "export_mode": "live",
            "time": round(time.time(), 3),
        }
        try:
            from . import memwatch as _memwatch

            body["memwatch"] = _memwatch.summary()
        except Exception:  # statusz must render even if memwatch breaks
            body["memwatch"] = None
        self._send(200, "application/json", json.dumps(body) + "\n")

    def _tracez(self):
        # the per-rank half of the router's /tracez (docs/
        # OBSERVABILITY.md §Request tracing): the recorder's bounded
        # ring of recently COMPLETED requests with their trace ids and
        # attributed causes — rollup-only, same jax-free contract as
        # the other routes
        body = {
            "recent": telemetry.recent_requests(),
            "time": round(time.time(), 3),
        }
        self._send(200, "application/json", json.dumps(body) + "\n")

    def _send(self, code: int, ctype: str, body: str):
        payload = body.encode("utf-8", "replace")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):
        # scrapes at 1 Hz must not spam the worker's stderr next to the
        # [rank N]-prefixed training logs; debug level keeps them findable
        _LOG.debug("%s %s", self.address_string(), fmt % args)


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.server: Optional[ThreadingHTTPServer] = None
        self.thread: Optional[threading.Thread] = None
        self.port: int = 0
        self.portfile: Optional[str] = None


_state = _State()


def enabled() -> bool:
    """Whether this process is currently serving /metrics."""
    return _state.server is not None


def port() -> int:
    """The bound port (0 when the endpoint is off)."""
    return _state.port


def _write_portfile(bound_port: int, host: str) -> Optional[str]:
    directory = os.environ.get("MX_TELEMETRY_DIR")
    if not directory:
        return None  # nowhere to advertise: endpoint still serves
    rank_id = telemetry.rank()
    path = portfile_path(directory, rank_id)
    # advertise a CONNECTABLE host: a wildcard bind is reachable on
    # loopback; a specific MX_METRICS_HOST (e.g. the host NIC) is not
    # necessarily on 127.0.0.1, so the supervisor must dial it as bound
    payload = {"rank": rank_id, "port": bound_port,
               "host": "127.0.0.1" if host in ("0.0.0.0", "::", "") else host,
               "pid": os.getpid(), "time": round(time.time(), 3)}
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        os.makedirs(directory, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # the supervisor never sees a torn portfile
    except OSError as e:
        _LOG.warning("metrics portfile write to %s failed: %s", path, e)
        return None
    return path


def start(base_port: Optional[int] = None) -> bool:
    """Start the endpoint (idempotent).  ``base_port`` overrides
    ``MX_METRICS_PORT`` (0 = ephemeral); returns True when a server is
    running after the call."""
    if base_port is None:
        base_port = _config_port()
        if base_port is None:
            return False
    host = os.environ.get("MX_METRICS_HOST", "127.0.0.1")
    bind_port = base_port + telemetry.rank() if base_port else 0
    with _state.lock:
        if _state.server is not None:
            return True
        try:
            server = ThreadingHTTPServer((host, bind_port), _Handler)
        except OSError as e:
            # a dead endpoint must not take training down with it
            _LOG.warning("metrics endpoint failed to bind %s:%d: %s",
                         host, bind_port, e)
            return False
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever,
                                  name="mx-metrics-server", daemon=True)
        thread.start()
        _state.server = server
        _state.thread = thread
        _state.port = server.server_address[1]
        _state.portfile = _write_portfile(_state.port, host)
    _LOG.info("metrics endpoint serving on %s:%d (/metrics /healthz "
              "/statusz)", host, _state.port)
    return True


def stop() -> None:
    """Shut the endpoint down and remove the portfile (tests; workers
    normally just exit — daemon threads die with the process and the
    supervisor treats an unreachable endpoint as down)."""
    with _state.lock:
        server, thread = _state.server, _state.thread
        portfile = _state.portfile
        _state.server = _state.thread = _state.portfile = None
        _state.port = 0
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5.0)
    if portfile:
        try:
            os.unlink(portfile)
        except OSError:
            pass


def maybe_start() -> bool:
    """Start iff ``MX_METRICS_PORT`` enables it — called at package
    import (workers inherit the variable from tools/launch.py)."""
    if _config_port() is None:
        return False
    return start()
