"""Evaluation metrics.

Reference parity: python/mxnet/metric.py (~L1-1500): EvalMetric base,
Accuracy, TopKAccuracy, F1, MAE/MSE/RMSE, CrossEntropy, Perplexity,
PearsonCorrelation, CompositeEvalMetric, create().
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from .base import MXNetError

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE",
           "RMSE", "CrossEntropy", "NegativeLogLikelihood", "Perplexity",
           "PearsonCorrelation", "Loss", "CompositeEvalMetric", "create"]

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        try:
            return _REGISTRY[metric.lower()](*args, **kwargs)
        except KeyError:
            raise MXNetError(f"unknown metric {metric!r}") from None
    raise MXNetError(f"cannot create metric from {metric!r}")


def _to_numpy(x):
    from .ndarray import NDArray

    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise MXNetError(
            f"Shape of labels {label_shape} does not match shape of "
            f"predictions {pred_shape}")


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if pred.ndim > label.ndim:
                pred = np.argmax(pred, axis=self.axis)
            pred = pred.astype(np.int32).ravel()
            label = label.astype(np.int32).ravel()
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += int((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert top_k > 1, "Use Accuracy if top_k is no more than 1"
        self.name += f"_{top_k}"

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(np.int32)
            assert pred.ndim == 2, "Predictions should be 2 dims"
            topk_idx = np.argsort(pred, axis=1)[:, -self.top_k:]
            hits = (topk_idx == label.reshape(-1, 1)).any(axis=1)
            self.sum_metric += int(hits.sum())
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = 0.0
        self._fp = 0.0
        self._fn = 0.0

    def reset(self):
        super().reset()
        if hasattr(self, "average"):
            self.reset_stats()

    @staticmethod
    def _f1_score(tp, fp, fn):
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        return (2 * precision * recall / (precision + recall)
                if precision + recall > 0 else 0.0)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        for label, pred in zip(labels, preds):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(np.int32)
            if pred.ndim > 1:
                pred = np.argmax(pred, axis=-1)
            pred = pred.astype(np.int32)
            if not np.all(np.isin(label, [0, 1])):
                raise MXNetError("F1 currently only supports binary classification.")
            tp = int(((pred == 1) & (label == 1)).sum())
            fp = int(((pred == 1) & (label == 0)).sum())
            fn = int(((pred == 0) & (label == 1)).sum())
            if self.average == "macro":
                # mean of per-batch F1 (reference default)
                self.sum_metric += self._f1_score(tp, fp, fn)
                self.num_inst += 1
            else:  # micro: global counts
                self._tp += tp
                self._fp += fp
                self._fn += fn
                self.sum_metric = self._f1_score(self._tp, self._fp, self._fn)
                self.num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_numpy(label).ravel()
            pred = _to_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[np.arange(label.shape[0]), label.astype(np.int64)]
            self.sum_metric += float((-np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            assert label.size == pred.size / pred.shape[-1]
            label = label.reshape(-1).astype(np.int64)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(np.log(np.maximum(1e-10, probs)).sum())
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        for label, pred in zip(labels, preds):
            label = _to_numpy(label).ravel()
            pred = _to_numpy(pred).ravel()
            self.sum_metric += float(np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Dummy metric for mean of (already computed) loss values."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        preds = preds if isinstance(preds, list) else [preds]
        for pred in preds:
            loss = float(_to_numpy(pred).sum())
            self.sum_metric += loss
            self.num_inst += int(np.prod(_to_numpy(pred).shape)) or 1


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        labels = labels if isinstance(labels, list) else [labels]
        preds = preds if isinstance(preds, list) else [preds]
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


# short aliases like the reference
_REGISTRY["acc"] = Accuracy
_REGISTRY["top_k_accuracy"] = TopKAccuracy
_REGISTRY["top_k_acc"] = TopKAccuracy
_REGISTRY["ce"] = CrossEntropy
_REGISTRY["nll_loss"] = NegativeLogLikelihood
_REGISTRY["pearsonr"] = PearsonCorrelation
