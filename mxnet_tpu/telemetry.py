"""Runtime telemetry: step metrics, retrace detection, heartbeats, and a
flight recorder (docs/OBSERVABILITY.md).

The reference MXNet answers "why is training slow / stuck?" with its
engine-level profiler brackets (src/profiler/); here whole steps fuse into
single XLA executables, so the observable unit is the *step*, not the op.
This module is the process-wide recorder every layer reports into:

  * step events from the compiled executors (``parallel/data_parallel.py``,
    ``symbol/executor.py``, the Gluon ``Trainer``): wall time, first-call
    compile vs steady-state execute, samples/sec, host<->device bytes;
  * **retrace detection**: every executor reports its jit call signature;
    when one executor accumulates more than ``MX_TELEMETRY_RETRACE_LIMIT``
    distinct signatures a rate-limited warning names the offending
    signature — the classic silent 10x slowdown of shape-churning input
    pipelines (each new shape forces a full XLA recompile);
  * collective events (op, nbytes, duration) from ``kvstore.py`` and
    ``parallel/dist.py``;
  * fault-tolerance lifecycle events (checkpoint save/load durations,
    digest fallbacks, rendezvous retries, restart count) from
    ``checkpoint.py`` / ``parallel/dist.py``;
  * **per-rank heartbeat files** (step + timestamp, atomically renamed)
    that the ``tools/launch.py`` supervisor polls to diagnose a hung rank
    *before* killing it.

Disabled (no ``MX_TELEMETRY_DIR``) the recorder no-ops: ``record*()`` and
``heartbeat()`` return immediately, so the hot step path pays only a
boolean check.  Retrace *detection* stays on — a microseconds-scale
signature build + set lookup per executor call — because the warning it
guards is precisely for runs nobody was watching closely enough to
enable telemetry on; ``MX_TELEMETRY_RETRACE_LIMIT=0`` switches it off
entirely (call sites check ``retrace_enabled()`` before building the
signature).

On-disk layout under ``MX_TELEMETRY_DIR`` (one stream per rank; the
filename patterns are mirrored in tools/launch.py, which must stay
importable without jax — keep them in sync)::

    rank-<R>.jsonl        append-only event stream, one JSON object/line:
                          {"t": <unix sec>, "kind": "...", "rank": R, ...}
    heartbeat-<R>.json    {"rank": R, "step": S, "time": <unix sec>,
                          "pid": P, "restart": K} — atomically replaced at
                          most every MX_HEARTBEAT_SEC seconds

Events buffer in memory (bounded) and a daemon thread flushes them every
``MX_TELEMETRY_FLUSH_SEC`` seconds; the last ``RING_SIZE`` events also live
in an in-process ring (the flight recorder) surfaced by ``summary()`` /
``flight_tail()``.

**Span tracing** (docs/OBSERVABILITY.md §Tracing & analysis): ``span(name,
**attrs)`` is a context manager emitting nested span events stamped with
the per-process monotonic clock (``mono``) so regions order exactly even
when the wall clock steps — one complete ``span`` event per region on hot
paths, or ``span_begin``/``span_end`` pairs (``paired=True``) for blocking
regions whose still-open begin is the flight-recorder's "died inside X"
clue.  A ``clock_anchor``
event — a ``(time.time(), perf_counter())`` pair written at enable() and
re-emitted on every flush — lets the analysis side (``export_chrome_trace``,
``tools/trace_report.py``) merge per-rank files onto ONE wall timeline
despite rank start-time skew.  Spans are on whenever the recorder is on;
``MX_TELEMETRY_SPANS=0`` is the kill switch.  ``export_chrome_trace(dir)``
merges every rank's stream into a Chrome/Perfetto trace-event JSON (one
track per rank, spans nested, collectives as flow events);
``render_prometheus(mode)`` renders an OpenMetrics exposition of the
``summary()`` rollups — ONE formatter behind two sinks:
``export_prometheus(path)`` (file snapshot, ``mode="atexit"``) and the
live per-rank HTTP endpoint in ``mxnet_tpu.metrics_server``
(``MX_METRICS_PORT``; ``mode="live"`` — docs/OBSERVABILITY.md §Live
metrics).  ``MX_TRACE_EXPORT`` (default off) runs the file exports
automatically at process exit.
"""
from __future__ import annotations

import atexit
import itertools
import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["enabled", "enable", "disable", "record", "record_step",
           "record_collective", "record_fused_update", "record_block_wait",
           "record_serve_request", "record_serve_state",
           "record_serve_cause", "recent_requests",
           "heartbeat", "note_signature", "summary", "flight_tail", "flush",
           "reset", "rank", "event_path", "heartbeat_path", "RING_SIZE",
           "span", "record_span", "spans_enabled", "export_chrome_trace",
           "export_prometheus", "render_prometheus", "health_snapshot",
           "stale_after_sec"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

# flight-recorder depth (in-process ring; the supervisor reads the JSONL
# file's tail instead, so this only bounds summary()/flight_tail())
RING_SIZE = 256
# nudge the flusher thread awake when this many events are pending, so
# serialization + disk I/O happen OFF the hot path (span tracing at ~10
# events/step would otherwise pay an inline flush every dozen steps)
_FLUSH_PENDING_MAX = 128
# hard backstop: if the flusher thread cannot keep up (or died), the
# recording thread flushes inline rather than growing memory unbounded
_FLUSH_PENDING_HARD = 4096
# distinct jit signatures one executor may accumulate before the retrace
# warning fires (override: MX_TELEMETRY_RETRACE_LIMIT)
_RETRACE_LIMIT_DEFAULT = 5


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def event_path(directory: str, rank_id: int) -> str:
    """Per-rank JSONL event stream path (mirrored in tools/launch.py)."""
    return os.path.join(directory, f"rank-{rank_id}.jsonl")


def heartbeat_path(directory: str, rank_id: int) -> str:
    """Per-rank heartbeat file path (mirrored in tools/launch.py)."""
    return os.path.join(directory, f"heartbeat-{rank_id}.json")


def rank() -> int:
    """This process's gang rank (0 for single-process runs)."""
    try:
        return int(os.environ.get("MX_PROC_ID",
                                  os.environ.get("DMLC_WORKER_ID", "0")))
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# recorder state
# ---------------------------------------------------------------------------
class _State:
    """All mutable recorder state in one bag so reset() is atomic."""

    def __init__(self):
        self.lock = threading.RLock()
        # serializes the actual file append: flush() may run concurrently
        # on the daemon flusher, an inline >=128-pending flush, and
        # atexit — interleaved write(2) calls would tear JSONL lines
        self.write_lock = threading.Lock()
        self.dir: Optional[str] = None
        self.rank: int = 0
        self.enabled = False
        self.ring: deque = deque(maxlen=RING_SIZE)
        # pending holds raw event DICTS: json serialization happens at
        # flush time (flusher thread / atexit), not on the hot path
        self.pending: List[dict] = []
        self.counts: Dict[str, int] = {}
        # executor -> {count, first_ms, total_ms, samples, bytes}
        self.steps: Dict[str, Dict[str, float]] = {}
        self.coll = {"count": 0, "bytes": 0, "total_ms": 0.0,
                     "compile_ms": 0.0}
        self.fused = {"count": 0, "n_params": 0, "n_buckets": 0,
                      "bytes": 0, "jitted_calls": 0}
        # serving rollups (docs/SERVING.md §SLO telemetry): per-request
        # aggregates + a bounded reservoir of end-to-end latencies for
        # the rolling p50/p99, + the queue/slot gauges the engine stamps
        # at every stream boundary
        self.serve = {"requests": 0, "tokens": 0, "queue_wait_ms": 0.0,
                      "prefill_ms": 0.0, "decode_ms": 0.0,
                      "lat_ms": deque(maxlen=512),
                      "ttft_ms": deque(maxlen=512),
                      "slo_ttft": 0, "slo_tpot": 0,
                      "queue_depth": 0, "active_slots": 0,
                      # precision label of the serving engine's compiled
                      # decode program (fp32 / int8 — docs/PRECISION.md)
                      "precision": "fp32",
                      # zero-downtime hot-swap counters: which weight
                      # generation is serving and how many swaps applied
                      # (docs/SERVING.md §Weight hot-swap)
                      "weight_generation": 0, "weight_swaps": 0,
                      # prefix-cache counters (docs/SERVING.md §Prefix
                      # cache): hits/misses across both entry kinds +
                      # how many prefix tokens skipped recompute
                      "prefix_hits": 0, "prefix_misses": 0,
                      "prefix_tokens_reused": 0,
                      # speculative decoding (§Speculative decoding):
                      # lifetime draft tokens proposed/accepted — the
                      # acceptance rate IS the speedup lever
                      "spec_rounds": 0, "spec_proposed": 0,
                      "spec_accepted": 0,
                      # request-tracing cause attribution (docs/
                      # OBSERVABILITY.md §Request tracing): completed
                      # requests bucketed by attributed tail cause
                      # (preempt/swap/cache_miss/failover/none) + one
                      # exemplar trace id per cause — the prometheus
                      # exemplar stand-in, bounded at one series/cause
                      "causes": {}, "cause_exemplars": {}}
        # newest completed requests (request_id/trace_id/cause/latency):
        # the per-rank /tracez ring metrics_server serves, sized by
        # MX_RQTRACE_TRACEZ_K at enable() (default 32)
        self.serve_recent: deque = deque(maxlen=32)
        # newest in-flight dispatch-window depth any executor reported
        # (record_step's inflight_depth field) — a /healthz input
        self.inflight_depth = 0
        self.ckpt = {"saves": 0, "save_ms": 0.0, "save_bytes": 0,
                     "loads": 0, "load_ms": 0.0, "fallbacks": 0}
        # executor -> {"sigs": set, "traces": int, "warned_at": int,
        #              "last_sig": str}
        self.retraces: Dict[str, Dict[str, Any]] = {}
        # span name -> {count, total_ms, max_ms}
        self.spans: Dict[str, Dict[str, float]] = {}
        self.flusher: Optional[threading.Thread] = None
        # record() sets this when pending crosses _FLUSH_PENDING_MAX so
        # the flusher wakes immediately instead of at its next cadence
        self.flush_wake = threading.Event()
        self.flush_sec = 1.0
        self.hb_interval = 5.0
        self.hb_last = 0.0
        self.hb_wall = 0.0
        self.hb_step = -1


_state = _State()


def enabled() -> bool:
    return _state.enabled


def enable(directory: Optional[str] = None) -> None:
    """Attach the JSONL sink (and heartbeats).  With no argument, reads
    ``MX_TELEMETRY_DIR``; a missing/empty directory leaves the recorder
    disabled.  Idempotent; safe to call from any thread."""
    directory = directory or os.environ.get("MX_TELEMETRY_DIR")
    if not directory:
        return
    with _state.lock:
        if _state.enabled and _state.dir == directory:
            return
        os.makedirs(directory, exist_ok=True)
        _state.dir = directory
        _state.rank = rank()
        _state.flush_sec = max(0.05, _env_float("MX_TELEMETRY_FLUSH_SEC", 1.0))
        _state.hb_interval = max(0.0, _env_float("MX_HEARTBEAT_SEC", 5.0))
        k = max(1, int(_env_float("MX_RQTRACE_TRACEZ_K", 32)))
        if k != _state.serve_recent.maxlen:
            _state.serve_recent = deque(_state.serve_recent, maxlen=k)
        _state.enabled = True
        if _state.flusher is None:
            _state.flusher = threading.Thread(
                target=_flusher_loop, name="mx-telemetry-flush", daemon=True)
            _state.flusher.start()
    record("start", pid=os.getpid(),
           restart=int(os.environ.get("MX_RESTART_COUNT", "0") or 0))
    # wall<->monotonic anchor: the merge key export_chrome_trace /
    # trace_report use to put every rank's mono-stamped spans on one wall
    # timeline (re-emitted on each flush — see flush())
    record("clock_anchor", wall=round(time.time(), 6),
           mono=round(time.perf_counter(), 6))


def disable() -> None:
    """Detach the sink (pending events are flushed first)."""
    flush()
    with _state.lock:
        _state.enabled = False


def reset() -> None:
    """Drop all aggregates, ring contents, and retrace history (tests)."""
    global _state
    flush()
    with _state.lock:
        fl = _state.flusher
        _state = _State()
        _state.flusher = fl  # one flusher thread per process is plenty


def _flusher_loop() -> None:
    while True:
        _state.flush_wake.wait(_state.flush_sec)
        _state.flush_wake.clear()
        try:
            flush()
        except Exception:  # a full disk must not kill the training process
            pass


def flush() -> None:
    """Append pending events to this rank's JSONL file.  Every batch ends
    with a fresh ``clock_anchor`` line (wall + monotonic pair): anchors are
    re-emitted so a merged-trace reader always finds one near the events it
    aligns, tolerating rank start-time skew and wall-clock steps."""
    st = _state
    # write_lock brackets snapshot + serialize + append: two concurrent
    # flushes (flusher thread vs the 4096-pending backstop or atexit)
    # must not reorder batches on disk — a span_begin landing after its
    # span_end would silently drop the pair from every trace consumer.
    # record() never touches write_lock, so the hot path is unaffected.
    with st.write_lock:
        with st.lock:
            if not st.pending or st.dir is None:
                return
            events, st.pending = st.pending, []
            path = event_path(st.dir, st.rank)
            rank_id = st.rank
        lines = []
        for ev in events:
            try:
                lines.append(json.dumps(ev) + "\n")
            except (TypeError, ValueError):
                ev = {k: (v if isinstance(v, (int, float, str, bool,
                                              type(None)))
                          else str(v)) for k, v in ev.items()}
                lines.append(json.dumps(ev) + "\n")
        wall = time.time()
        lines.append(json.dumps(
            {"t": round(wall, 4), "kind": "clock_anchor", "rank": rank_id,
             "wall": round(wall, 6),
             "mono": round(time.perf_counter(), 6)}) + "\n")
        try:
            with open(path, "a") as f:
                f.write("".join(lines))
        except OSError as e:
            _LOG.warning("telemetry flush to %s failed: %s", path, e)


atexit.register(flush)


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------
_SPAN_IDS = itertools.count(1)
_span_local = threading.local()  # per-thread nesting stack of span ids


def spans_enabled() -> bool:
    """Spans ride the recorder: on whenever telemetry is on, unless
    ``MX_TELEMETRY_SPANS=0`` kills them (the knob exists so a production
    run can keep step events + heartbeats while dropping the ~8 extra
    events per step the span layer adds)."""
    if not _state.enabled:
        return False
    return os.environ.get("MX_TELEMETRY_SPANS", "1").lower() not in (
        "0", "false", "off")


class _NullSpan:
    """Shared no-op context manager: span() allocates nothing when off."""

    __slots__ = ()

    span_id = 0  # parity with _Span: propagation call sites need an int

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_name", "_attrs", "_id", "_t0", "_parent", "_depth",
                 "_paired")

    def __init__(self, name: str, attrs: dict, paired: bool):
        self._name = name
        self._attrs = attrs
        self._paired = paired
        self._id = 0

    @property
    def span_id(self) -> int:
        """This span's id once entered (0 before) — what the Router puts
        in the outgoing ``X-MX-Trace`` ``parent=`` field so a replica can
        name its upstream span."""
        return self._id

    def __enter__(self):
        stack = getattr(_span_local, "stack", None)
        if stack is None:
            stack = _span_local.stack = []
        self._id = next(_SPAN_IDS)
        self._parent = stack[-1] if stack else 0
        self._depth = len(stack)
        stack.append(self._id)
        self._t0 = time.perf_counter()
        if self._paired:
            # mono is THE ordering/merge key (export_chrome_trace aligns
            # it to the gang wall timeline via the clock_anchor events);
            # the event's own "t" stays the wall stamp for humans reading
            # raw JSONL
            record("span_begin", name=self._name, span=self._id,
                   parent=self._parent, depth=self._depth,
                   tid=threading.get_ident(),
                   mono=round(self._t0, 6), **self._attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        dur_ms = (t1 - self._t0) * 1e3
        stack = getattr(_span_local, "stack", None)
        if stack and stack[-1] == self._id:
            stack.pop()
        elif stack and self._id in stack:
            # a nested span leaked past its parent's exit (exception taking
            # a non-local path): unwind to self so nesting self-heals
            del stack[stack.index(self._id):]
        with _state.lock:
            agg = _state.spans.setdefault(
                self._name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
            agg["count"] += 1
            agg["total_ms"] += dur_ms
            agg["max_ms"] = max(agg["max_ms"], dur_ms)
        if self._paired:
            end = dict(name=self._name, span=self._id,
                       tid=threading.get_ident(), mono=round(t1, 6),
                       dur_ms=round(dur_ms, 3))
            if exc_type is not None:
                end["error"] = exc_type.__name__
            record("span_end", **end)
        else:
            # one complete event for the whole region: half the event
            # volume of a begin/end pair — the hot-path per-step form
            ev = dict(name=self._name, span=self._id, parent=self._parent,
                      depth=self._depth, tid=threading.get_ident(),
                      mono=round(self._t0, 6), dur_ms=round(dur_ms, 3),
                      **self._attrs)
            if exc_type is not None:
                ev["error"] = exc_type.__name__
            record("span", **ev)
        return False


def record_span(name: str, t0: float, t1: float, **attrs) -> None:
    """Retroactively emit one completed span from a measured
    ``perf_counter`` interval — the zero-cost-when-idle form for hot-path
    waits that usually DON'T happen (a non-blocking ``make_room``): the
    caller times the interval with two perf_counter reads and records a
    span only when it actually waited, instead of paying events per step
    for a 0ms fact.  Emitted with correct nesting metadata (parent = the
    caller's current open span) so the merged trace renders it exactly
    like a ``span()`` region."""
    if not spans_enabled():
        return
    dur_ms = (t1 - t0) * 1e3
    sid = next(_SPAN_IDS)
    stack = getattr(_span_local, "stack", None)
    parent = stack[-1] if stack else 0
    depth = len(stack) if stack else 0
    record("span", name=name, span=sid, parent=parent, depth=depth,
           tid=threading.get_ident(), mono=round(t0, 6),
           dur_ms=round(dur_ms, 3), **attrs)
    with _state.lock:
        agg = _state.spans.setdefault(
            name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += dur_ms
        agg["max_ms"] = max(agg["max_ms"], dur_ms)


def span(name: str, paired: bool = False, **attrs):
    """Context manager tracing one nested timing region, carrying a span
    id, the parent span's id, nesting ``depth``, the thread id, and the
    monotonic clock — everything ``export_chrome_trace`` /
    ``tools/trace_report.py`` need to rebuild the gang timeline.  Returns
    a shared no-op object when spans are off, so hot paths pay one env
    check when disabled.

    By default the whole region lands as ONE complete ``span`` event at
    exit (half the event volume — the per-step hot-path form).
    ``paired=True`` emits ``span_begin``/``span_end`` events instead: use
    it for regions that BLOCK (device waits, collectives, checkpoint
    I/O), where a crashed/hung rank's flight-recorder tail must show the
    still-open ``span_begin`` — "died inside X" is the post-mortem
    answer.  (``paired`` is reserved; it cannot be used as an attr name.)

    Spans measure HOST wall between enter and exit: around an async jax
    dispatch that is dispatch cost, not device time (the same contract as
    ``record_step`` — see its docstring)."""
    if not spans_enabled():
        return _NULL_SPAN
    return _Span(name, attrs, paired)


# ---------------------------------------------------------------------------
# event recording
# ---------------------------------------------------------------------------
def record(kind: str, **fields) -> None:
    """Record one event.  No-op unless the recorder is enabled.

    Span begin/end events skip the in-process flight ring: at ~8 per step
    they would evict the step/collective/checkpoint history the ring
    exists to preserve for post-mortems.  They still hit the JSONL sink
    (the analysis surface) and the ``summary()`` span aggregates."""
    if not _state.enabled:
        return
    ev = {"t": round(time.time(), 4), "kind": kind, "rank": _state.rank}
    ev.update(fields)
    with _state.lock:
        _state.counts[kind] = _state.counts.get(kind, 0) + 1
        if not kind.startswith("span"):
            _state.ring.append(ev)
        _state.pending.append(ev)
        n_pending = len(_state.pending)
    if n_pending >= _FLUSH_PENDING_MAX:
        if n_pending >= _FLUSH_PENDING_HARD or _state.flusher is None:
            flush()  # backstop: never let a stalled flusher grow memory
        else:
            _state.flush_wake.set()  # serialization + I/O off the hot path


def record_step(executor: str, step: int, wall_s: float,
                samples: Optional[int] = None, transfer_bytes: int = 0,
                traced: bool = False, h2d_overlapped: int = 0,
                **fields) -> None:
    """One executor step.  ``traced=True`` marks a first-call/retrace step
    whose wall time includes trace+compile; those are aggregated separately
    so steady-state samples/sec is not polluted by compile time.

    ``wall_s`` is the python-side wall of the step call — the recorder
    deliberately does NOT block_until_ready (forcing a device sync per
    step would serialize the dispatch pipeline the observability layer is
    meant to leave undisturbed).  Under async dispatch a single step's
    wall is dispatch cost, not device time; over a sustained loop the
    dispatch queue backpressures and per-step walls converge to true step
    cadence, so the AGGREGATES (mean_exec_ms, samples_per_sec over many
    steps) are meaningful while the first few per-step numbers undercount.
    For exact per-program device times use mx.profiler (its timed_call
    blocks by design).

    ``h2d_overlapped`` counts the subset of ``transfer_bytes`` that a
    device prefetcher staged in the background (already resident when the
    step ran) — the async-pipeline overlap evidence.  Extra async fields
    travel via ``**fields``: ``inflight_depth`` (pending window depth
    after this dispatch) and ``block_wait_ms`` (time this dispatch spent
    blocked because the window was full)."""
    if not _state.enabled:
        return
    wall_ms = wall_s * 1e3
    with _state.lock:
        st = _state.steps.setdefault(executor, _new_step_agg())
        st["count"] += 1
        if traced:
            st["compile_count"] += 1
            st["compile_ms"] += wall_ms
        else:
            st["exec_ms"] += wall_ms
            if samples:
                st["samples"] += int(samples)
        st["bytes"] += int(transfer_bytes)
        st["overlap_bytes"] += int(h2d_overlapped)
        if "inflight_depth" in fields:
            _state.inflight_depth = int(fields["inflight_depth"])
    ev = dict(executor=executor, step=int(step), wall_ms=round(wall_ms, 3),
              traced=bool(traced), **fields)
    if samples is not None:
        ev["samples"] = int(samples)
        if wall_s > 0:
            ev["samples_per_sec"] = round(samples / wall_s, 2)
    if transfer_bytes:
        ev["transfer_bytes"] = int(transfer_bytes)
    if h2d_overlapped:
        ev["h2d_overlapped"] = int(h2d_overlapped)
    record("step", **ev)


def _new_step_agg() -> Dict[str, float]:
    return {"count": 0, "compile_count": 0, "compile_ms": 0.0,
            "exec_ms": 0.0, "samples": 0, "bytes": 0,
            "overlap_bytes": 0, "block_wait_ms": 0.0}


def record_block_wait(executor: str, wall_s: float) -> None:
    """Host time spent BLOCKED on the device for one executor: a forced
    readback (``AsyncLoss.wait``), a full in-flight window, or a fence
    sync.  Aggregate-only (no per-event line — a hot loop forces every
    step); ``summary()['steps'][executor]['block_wait_ms']`` is the
    rollup that shows how much wall time the host truly lost to the
    device, the before/after number for the async pipeline."""
    if not _state.enabled or wall_s <= 0:
        return
    with _state.lock:
        st = _state.steps.setdefault(executor, _new_step_agg())
        st["block_wait_ms"] += wall_s * 1e3


def record_collective(op: str, nbytes: int, wall_s: float,
                      traced: bool = False, **fields) -> None:
    """One collective (kvstore reduce, global allreduce, ...).

    ``traced=True`` marks a first-use call whose wall includes the jit
    trace + XLA compile of the collective program; it aggregates into
    ``compile_ms`` so comm cost is never conflated with compile cost."""
    if not _state.enabled:
        return
    with _state.lock:
        _state.coll["count"] += 1
        _state.coll["bytes"] += int(nbytes)
        if traced:
            _state.coll["compile_ms"] += wall_s * 1e3
        else:
            _state.coll["total_ms"] += wall_s * 1e3
    record("collective", op=op, nbytes=int(nbytes),
           wall_ms=round(wall_s * 1e3, 3), traced=bool(traced), **fields)


def record_fused_update(n_params: int, n_buckets: int, nbytes: int,
                        n_jitted_calls: int, **fields) -> None:
    """One fused optimizer step (docs/PERFORMANCE.md): how many params
    updated, through how many gradient buckets and jitted update calls —
    the before/after evidence that the O(n_params) dispatch storm
    collapsed to O(1).  Aggregated under ``summary()['fused_update']``."""
    if not _state.enabled:
        return
    with _state.lock:
        f = _state.fused
        f["count"] += 1
        f["n_params"] += int(n_params)
        f["n_buckets"] += int(n_buckets)
        f["bytes"] += int(nbytes)
        f["jitted_calls"] += int(n_jitted_calls)
    record("fused_update", n_params=int(n_params), n_buckets=int(n_buckets),
           nbytes=int(nbytes), n_jitted_calls=int(n_jitted_calls), **fields)


def _slo_ms(name: str) -> float:
    """A latency SLO threshold in ms; 0/unset/garbage = no SLO."""
    return max(0.0, _env_float(name, 0.0))


def record_serve_request(queue_wait_ms: float = 0.0,
                         prefill_ms: float = 0.0, decode_ms: float = 0.0,
                         tokens: int = 0, ttft_ms: float = 0.0,
                         total_ms: Optional[float] = None,
                         **fields) -> None:
    """One COMPLETED serving request (mxnet_tpu.serving.engine): how
    long it queued, the prefill dispatch wall, the decode wall, how
    many tokens it produced, and the submission->first-token wall
    (``ttft_ms``, queue wait included — the user-visible TTFT, stamped
    at stream-boundary resolution).  End-to-end
    latency (the SLO number) is ``total_ms`` when the caller measured
    the true submit->finish wall (the serving engine does — a PREEMPTED
    request's discarded first service period must count toward its
    latency even though its per-leg fields cover only the last
    admission), else the sum of the three legs; bounded reservoirs of
    the newest 512 latencies/TTFTs back the rolling p50/p99 in
    ``summary()['serving']`` and the ``mx_serve_*`` gauges in
    :func:`render_prometheus`.  Per-request events land in the flight
    ring, so a gang post-mortem tail shows the last served requests.

    SLO accounting (docs/SERVING.md §SLO telemetry): with
    ``MX_SERVE_SLO_TTFT_MS`` / ``MX_SERVE_SLO_TPOT_MS`` set (>0), a
    request whose TTFT exceeds the former or whose time-per-output-token
    (decode wall / tokens) exceeds the latter bumps
    ``mx_serve_slo_violations_total{stage=...}`` and leaves a
    ``serve_slo_violation`` event naming the request.

    Request tracing (docs/OBSERVABILITY.md §Request tracing): ``trace_id``
    and ``cause`` travel in ``**fields`` onto the event; a non-``none``
    cause also bumps the per-cause counter behind
    ``mx_serve_request_cause_total`` and replaces that cause's exemplar
    (newest trace id + latency — bounded at one series per cause).  Every
    completed request additionally lands in the /tracez recent ring."""
    if not _state.enabled:
        return
    latency = (float(total_ms) if total_ms is not None else
               float(queue_wait_ms) + float(prefill_ms) + float(decode_ms))
    slo_ttft = _slo_ms("MX_SERVE_SLO_TTFT_MS")
    slo_tpot = _slo_ms("MX_SERVE_SLO_TPOT_MS")
    tpot_ms = float(decode_ms) / tokens if tokens else 0.0
    violations = []
    if slo_ttft and float(ttft_ms) > slo_ttft:
        violations.append(("ttft", round(float(ttft_ms), 3), slo_ttft))
    if slo_tpot and tpot_ms > slo_tpot:
        violations.append(("tpot", round(tpot_ms, 3), slo_tpot))
    cause = str(fields.get("cause") or "none")
    trace_id = fields.get("trace_id")
    with _state.lock:
        sv = _state.serve
        sv["requests"] += 1
        sv["tokens"] += int(tokens)
        sv["queue_wait_ms"] += float(queue_wait_ms)
        sv["prefill_ms"] += float(prefill_ms)
        sv["decode_ms"] += float(decode_ms)
        sv["lat_ms"].append(latency)
        if ttft_ms:
            sv["ttft_ms"].append(float(ttft_ms))
        for stage, _v, _t in violations:
            sv[f"slo_{stage}"] += 1
        if cause != "none":
            sv["causes"][cause] = sv["causes"].get(cause, 0) + 1
            if trace_id:
                sv["cause_exemplars"][cause] = {
                    "trace_id": str(trace_id),
                    "latency_ms": round(latency, 3)}
        _state.serve_recent.append({
            "t": round(time.time(), 3),
            "request_id": fields.get("request_id"),
            "trace_id": trace_id,
            "cause": cause,
            "latency_ms": round(latency, 3),
            "ttft_ms": round(float(ttft_ms), 3),
            "tokens": int(tokens),
            "reason": fields.get("reason"),
            "slo_violated": [stage for stage, _v, _t in violations]})
    record("serve_request", queue_wait_ms=round(queue_wait_ms, 3),
           prefill_ms=round(prefill_ms, 3), decode_ms=round(decode_ms, 3),
           latency_ms=round(latency, 3), tokens=int(tokens),
           ttft_ms=round(float(ttft_ms), 3), **fields)
    for stage, value_ms, threshold_ms in violations:
        record("serve_slo_violation", stage=stage, value_ms=value_ms,
               threshold_ms=threshold_ms,
               request_id=fields.get("request_id"),
               trace_id=trace_id)


def record_serve_cause(cause: str, trace_id: Optional[str] = None,
                       latency_ms: float = 0.0, **fields) -> None:
    """Attribute a tail cause OUTSIDE the engine's completion path — the
    Router calls this for ``failover`` (the engine never sees the dead
    replica's request) — bumping the same per-cause counter/exemplar
    ``record_serve_request`` feeds, plus a ``serve_cause`` event for the
    merged trace."""
    if not _state.enabled:
        return
    cause = str(cause)
    with _state.lock:
        sv = _state.serve
        sv["causes"][cause] = sv["causes"].get(cause, 0) + 1
        if trace_id:
            sv["cause_exemplars"][cause] = {
                "trace_id": str(trace_id),
                "latency_ms": round(float(latency_ms), 3)}
    record("serve_cause", cause=cause, trace_id=trace_id,
           latency_ms=round(float(latency_ms), 3), **fields)


def recent_requests() -> List[dict]:
    """The newest completed serving requests (trace id, attributed cause,
    latency — oldest first), bounded by ``MX_RQTRACE_TRACEZ_K``: the
    per-rank half of the /tracez surface (metrics_server serves it;
    the Router serves its own cross-replica view)."""
    with _state.lock:
        return [dict(r) for r in _state.serve_recent]


def record_serve_state(queue_depth: int, active_slots: int,
                       precision: Optional[str] = None) -> None:
    """Queue-depth / active-slot gauges, stamped by the serving engine
    at every stream boundary (aggregate-only: no per-boundary event —
    one boundary per few decode steps would drown the flight ring).
    ``precision`` labels which dtype program is serving (fp32/int8 —
    surfaces as ``mx_serve_precision_info`` and in
    ``summary()['serving']``)."""
    if not _state.enabled:
        return
    with _state.lock:
        _state.serve["queue_depth"] = int(queue_depth)
        _state.serve["active_slots"] = int(active_slots)
        if precision is not None:
            _state.serve["precision"] = str(precision)


def record_weight_swap(generation: int, staged_bytes: int = 0,
                       verify_ms: float = 0.0, flip_ms: float = 0.0,
                       **fields) -> None:
    """One APPLIED serving weight hot-swap (docs/SERVING.md §Weight
    hot-swap): bumps the swap counter, publishes the new generation
    gauge (``mx_serve_weight_generation``) and records a ``weight_swap``
    event carrying staged bytes plus verify/flip wall.  Rejected swaps
    record a plain ``weight_swap`` event with ``rejected=True`` at the
    call site instead — they never advance the generation."""
    if not _state.enabled:
        return
    with _state.lock:
        _state.serve["weight_generation"] = int(generation)
        _state.serve["weight_swaps"] += 1
    record("weight_swap", generation=int(generation),
           staged_bytes=int(staged_bytes),
           verify_ms=round(float(verify_ms), 3),
           flip_ms=round(float(flip_ms), 3), **fields)


def record_serve_prefix(kind: str, hit: bool, tokens: int = 0,
                        **fields) -> None:
    """One prefix-cache lookup (mxnet_tpu.serving.engine — docs/
    SERVING.md §Prefix cache).  ``kind`` is the entry family ("pages"
    for forked KV pages, "prefill" for reused prefill rows); a hit adds
    ``tokens`` to the reused-token counter (prefill/ingest work skipped).
    Aggregate-only counters + one flight-ring event per lookup — cheap
    at serving cadence (one lookup per admission, never per step)."""
    if not _state.enabled:
        return
    with _state.lock:
        sv = _state.serve
        sv["prefix_hits" if hit else "prefix_misses"] += 1
        if hit:
            sv["prefix_tokens_reused"] += int(tokens)
    record("serve_prefix", entry_kind=str(kind), hit=bool(hit),
           tokens=int(tokens), **fields)


def record_spec_verify(proposed: int, accepted: int, **fields) -> None:
    """One speculative verify boundary (mxnet_tpu.serving.engine —
    docs/SERVING.md §Speculative decoding): how many draft tokens the
    boundary proposed across slots and how many the target accepted.
    The lifetime acceptance rate (accepted/proposed) surfaces in
    ``summary()['serving']['spec']`` and ``mx_serve_spec_accept_rate`` —
    it is the whole speedup story: every accepted token is a decode
    step the engine never dispatched."""
    if not _state.enabled:
        return
    with _state.lock:
        sv = _state.serve
        sv["spec_rounds"] += 1
        sv["spec_proposed"] += int(proposed)
        sv["spec_accepted"] += int(accepted)
    record("spec_verify", proposed=int(proposed), accepted=int(accepted),
           **fields)


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list (stdlib-only —
    telemetry must not import numpy)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(math.ceil(q / 100.0 * len(sorted_vals))) - 1))
    return sorted_vals[idx]


def record_checkpoint(event: str, step: int, wall_s: float = 0.0,
                      nbytes: int = 0, **fields) -> None:
    """Checkpoint lifecycle: event in {save, load, fallback}."""
    if not _state.enabled:
        return
    with _state.lock:
        c = _state.ckpt
        if event == "save":
            c["saves"] += 1
            c["save_ms"] += wall_s * 1e3
            c["save_bytes"] += int(nbytes)
        elif event == "load":
            c["loads"] += 1
            c["load_ms"] += wall_s * 1e3
        elif event == "fallback":
            c["fallbacks"] += 1
    ev = dict(step=int(step), **fields)
    if wall_s:
        ev["wall_ms"] = round(wall_s * 1e3, 3)
    if nbytes:
        ev["nbytes"] = int(nbytes)
    record(f"checkpoint_{event}", **ev)


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------
def heartbeat(step: int, force: bool = False) -> None:
    """Write this rank's heartbeat file (atomic rename), rate-limited to
    one write per ``MX_HEARTBEAT_SEC``.  No-op when telemetry is disabled.

    The reported step is MONOTONIC (max over all reports): several layers
    heartbeat with their own counters — e.g. after a supervised restart
    the restored AsyncCheckpointer reports the global step while a fresh
    Trainer counts from 1 — and the supervisor's "last heartbeat at step
    S" diagnosis must not flap between them."""
    if not _state.enabled or _state.dir is None:
        return
    now = time.monotonic()
    with _state.lock:
        if not force and _state.hb_last and \
                now - _state.hb_last < _state.hb_interval:
            return
        _state.hb_last = now
        # wall stamp of the newest beat: export_prometheus derives the
        # mx_heartbeat_age_seconds gauge from it
        _state.hb_wall = time.time()
        step = _state.hb_step = max(int(step), _state.hb_step)
        directory, rank_id = _state.dir, _state.rank
    payload = {"rank": rank_id, "step": int(step),
               "time": round(time.time(), 3), "pid": os.getpid(),
               "restart": int(os.environ.get("MX_RESTART_COUNT", "0") or 0)}
    path = heartbeat_path(directory, rank_id)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # readers never see a torn heartbeat
    except OSError as e:
        _LOG.warning("heartbeat write to %s failed: %s", path, e)


# ---------------------------------------------------------------------------
# retrace detection
# ---------------------------------------------------------------------------
def _retrace_limit() -> int:
    try:
        return int(os.environ.get("MX_TELEMETRY_RETRACE_LIMIT",
                                  _RETRACE_LIMIT_DEFAULT))
    except (TypeError, ValueError):
        return _RETRACE_LIMIT_DEFAULT


def retrace_enabled() -> bool:
    """Retrace detection runs by default (even without a telemetry sink —
    it exists for runs nobody instrumented); ``MX_TELEMETRY_RETRACE_LIMIT=0``
    is the kill switch for hot loops where even the per-call signature
    build must go."""
    return _retrace_limit() > 0


# an executor name past this many registry entries folds into one shared
# overflow bucket: a script that builds a fresh executor per batch must
# not grow the registry forever — and since each such instance contributes
# its (distinct-shaped) first signature to the SAME bucket, the storm the
# per-instance keys would hide is detected there instead
_RETRACE_REGISTRY_MAX = 1024
_OVERFLOW_KEY = "<executor-churn-overflow>"


def note_signature(executor: str, signature) -> bool:
    """Report one executor call's jit signature (shapes/dtypes/static args).

    Returns True when the signature is NEW for this executor — i.e. jax.jit
    will trace and XLA will compile on this call.  When an executor
    accumulates more than the retrace limit of distinct signatures, emits a
    rate-limited warning naming the newest signature (then again only each
    time the count doubles — a storm logs a handful of lines, not one per
    step)."""
    if not retrace_enabled():
        return False
    with _state.lock:
        if (executor not in _state.retraces
                and len(_state.retraces) >= _RETRACE_REGISTRY_MAX):
            executor = _OVERFLOW_KEY
        ent = _state.retraces.setdefault(
            executor, {"sigs": set(), "traces": 0, "warned_at": 0,
                       "last_sig": ""})
        if signature in ent["sigs"]:
            return False
        if len(ent["sigs"]) >= 4096:
            # bounded memory even in a storm: evict one (arbitrary) stored
            # signature rather than dropping the NEW one — a pipeline that
            # churns past the cap and then stabilizes must find its final
            # signature in the set, not be re-counted as a fresh trace
            # (and re-warned) on every remaining step of the run
            ent["sigs"].pop()
        ent["sigs"].add(signature)
        ent["traces"] += 1
        # truncate at store time: summary() embeds last_sig verbatim into
        # bench records and dumps() output — a multi-KB feed signature
        # must not ride along whole
        ent["last_sig"] = str(signature)[:400]
        n = ent["traces"]
        limit = _retrace_limit()
        warn = n > limit and (ent["warned_at"] == 0
                              or n >= 2 * ent["warned_at"])
        if warn:
            ent["warned_at"] = n
    if warn:
        _LOG.warning(
            "executor %s has traced %d distinct signatures (retrace limit "
            "%d); newest: %s.  Every new input shape/dtype forces a full "
            "XLA recompile — the classic silent 10x slowdown.  Pad or "
            "bucket inputs to stable shapes (see docs/OBSERVABILITY.md).",
            executor, n, limit, str(signature)[:400])
        record("retrace", executor=executor, traces=n,
               signature=str(signature)[:400])
    return True


# ---------------------------------------------------------------------------
# rollups
# ---------------------------------------------------------------------------
def flight_tail(k: int = 20) -> List[dict]:
    """The last k events recorded in this process (newest last)."""
    with _state.lock:
        return list(_state.ring)[-k:]


def _serving_rollup() -> dict:
    """summary()['serving'] block (caller holds _state.lock)."""
    sv = _state.serve
    lat = sorted(sv["lat_ms"])
    ttft = sorted(sv["ttft_ms"])
    return {
        "requests": sv["requests"],
        "tokens": sv["tokens"],
        "queue_wait_ms": round(sv["queue_wait_ms"], 3),
        "prefill_ms": round(sv["prefill_ms"], 3),
        "decode_ms": round(sv["decode_ms"], 3),
        "p50_latency_ms": round(_percentile(lat, 50), 3),
        "p99_latency_ms": round(_percentile(lat, 99), 3),
        "p50_ttft_ms": round(_percentile(ttft, 50), 3),
        "p99_ttft_ms": round(_percentile(ttft, 99), 3),
        "slo_violations": {"ttft": sv["slo_ttft"], "tpot": sv["slo_tpot"]},
        "queue_depth": sv["queue_depth"],
        "active_slots": sv["active_slots"],
        "precision": sv.get("precision", "fp32"),
        "weight_generation": sv.get("weight_generation", 0),
        "weight_swaps": sv.get("weight_swaps", 0),
        "prefix_cache": {
            "hits": sv.get("prefix_hits", 0),
            "misses": sv.get("prefix_misses", 0),
            "tokens_reused": sv.get("prefix_tokens_reused", 0),
            "hit_rate": round(
                sv.get("prefix_hits", 0)
                / max(1, sv.get("prefix_hits", 0)
                      + sv.get("prefix_misses", 0)), 4),
        },
        "spec": {
            "rounds": sv.get("spec_rounds", 0),
            "proposed": sv.get("spec_proposed", 0),
            "accepted": sv.get("spec_accepted", 0),
            "accept_rate": round(
                sv.get("spec_accepted", 0)
                / max(1, sv.get("spec_proposed", 0)), 4),
        },
        "causes": dict(sv.get("causes", {})),
        "cause_exemplars": {k: dict(v) for k, v in
                            sv.get("cause_exemplars", {}).items()},
    }


def summary() -> dict:
    """JSON-serializable rollup of everything recorded so far.  Works even
    when the recorder is disabled (retrace tracking is always on)."""
    with _state.lock:
        steps = {}
        for name, st in _state.steps.items():
            exec_count = st["count"] - st["compile_count"]
            row = {
                "count": st["count"],
                "compile_count": st["compile_count"],
                "compile_ms": round(st["compile_ms"], 3),
                "exec_ms": round(st["exec_ms"], 3),
                "transfer_bytes": st["bytes"],
                "h2d_overlapped_bytes": st.get("overlap_bytes", 0),
                "block_wait_ms": round(st.get("block_wait_ms", 0.0), 3),
            }
            if exec_count > 0:
                row["mean_exec_ms"] = round(st["exec_ms"] / exec_count, 3)
            if st["samples"] and st["exec_ms"] > 0:
                row["samples_per_sec"] = round(
                    st["samples"] / (st["exec_ms"] / 1e3), 2)
            steps[name] = row
        retraces = {
            name: {"traces": ent["traces"], "last_signature": ent["last_sig"]}
            for name, ent in _state.retraces.items()
        }
        out = {
            "enabled": _state.enabled,
            "rank": _state.rank if _state.enabled else rank(),
            "dir": _state.dir,
            "events": dict(_state.counts),
            "steps": steps,
            "collectives": {
                "count": _state.coll["count"],
                "bytes": _state.coll["bytes"],
                "total_ms": round(_state.coll["total_ms"], 3),
                "compile_ms": round(_state.coll["compile_ms"], 3),
            },
            "checkpoints": {k: (round(v, 3) if isinstance(v, float) else v)
                            for k, v in _state.ckpt.items()},
            "fused_update": dict(_state.fused),
            "serving": _serving_rollup(),
            "spans": {
                name: {"count": agg["count"],
                       "total_ms": round(agg["total_ms"], 3),
                       "max_ms": round(agg["max_ms"], 3)}
                for name, agg in _state.spans.items()
            },
            "retraces": retraces,
            "inflight_depth": _state.inflight_depth,
            "restart_count": int(
                os.environ.get("MX_RESTART_COUNT", "0") or 0),
        }
    return out


# ---------------------------------------------------------------------------
# health (metrics_server /healthz; the same staleness rule the
# tools/launch.py supervisor applies to heartbeat FILES)
# ---------------------------------------------------------------------------
def stale_after_sec() -> float:
    """Seconds without a heartbeat before this rank counts as stale:
    several missed beats, floored so sub-second test configs don't flag
    healthy processes on a loaded host (mirrored in tools/launch.py
    _HeartbeatMonitor — keep in sync)."""
    return max(2.0, 5.0 * max(0.0, _env_float("MX_HEARTBEAT_SEC", 5.0)))


def health_snapshot() -> dict:
    """Liveness verdict from the recorder's locked rollups only (no jax,
    no device sync — the /healthz contract): heartbeat age vs the
    supervisor's staleness rule, the last heartbeat step, the gang
    restart count, and the in-flight dispatch depth.  ``healthy`` is
    False only when heartbeats were flowing and then stopped; a process
    that never heartbeat (telemetry off, or startup) reports
    ``heartbeat_age_s: None`` and stays healthy — liveness of the HTTP
    thread itself is then the only claim being made."""
    stale_after = stale_after_sec()
    with _state.lock:
        hb_wall = _state.hb_wall
        hb_step = _state.hb_step
        inflight = _state.inflight_depth
        sv_depth = _state.serve["queue_depth"]
        sv_slots = _state.serve["active_slots"]
        on = _state.enabled
    age = max(0.0, time.time() - hb_wall) if hb_wall else None
    reasons = []
    if age is not None and age > stale_after:
        reasons.append(f"last heartbeat {age:.1f}s ago "
                       f"(stale after {stale_after:.1f}s)")
    return {
        "healthy": not reasons,
        "reasons": reasons,
        "telemetry_enabled": on,
        "rank": _state.rank if on else rank(),
        "heartbeat_age_s": round(age, 3) if age is not None else None,
        "stale_after_s": round(stale_after, 3),
        "last_step": hb_step if hb_step >= 0 else None,
        "restart_count": int(os.environ.get("MX_RESTART_COUNT", "0") or 0),
        "inflight_depth": inflight,
        "serve_queue_depth": sv_depth,
        "serve_active_slots": sv_slots,
        "pid": os.getpid(),
        "time": round(time.time(), 3),
    }


# ---------------------------------------------------------------------------
# exporters (docs/OBSERVABILITY.md §Tracing & analysis)
# ---------------------------------------------------------------------------
def _iter_rank_files(directory: str):
    """(rank, path) for every rank-<R>.jsonl under ``directory``."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return
    for name in names:
        if name.startswith("rank-") and name.endswith(".jsonl"):
            try:
                r = int(name[len("rank-"):-len(".jsonl")])
            except ValueError:
                continue
            yield r, os.path.join(directory, name)


def _load_rank_events(path: str) -> List[dict]:
    events = []
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # torn final line of a SIGKILLed rank
                if isinstance(ev, dict):
                    events.append(ev)
    except OSError:
        pass
    return events


def _mono_offset(events: List[dict], rank_id) -> float:
    """Fallback wall - perf_counter offset for an old-format stream with
    NO ``clock_anchor`` events (anchored streams align to the nearest
    preceding anchor in export_chrome_trace instead): derived from the
    first mono-stamped event's own wall stamp, with a warning —
    alignment then absorbs that event's record->flush latency."""
    for e in events:
        if "mono" in e and "t" in e:
            _LOG.warning(
                "rank %s stream has no clock_anchor events (old-format "
                "file?): aligning its spans from event wall stamps — "
                "cross-rank timeline may be skewed by flush latency",
                rank_id)
            return float(e["t"]) - float(e["mono"])
    return 0.0


def export_chrome_trace(directory: Optional[str] = None,
                        out: Optional[str] = None) -> Optional[str]:
    """Merge every rank's JSONL stream under ``directory`` (default: the
    live recorder's dir) into ONE Chrome/Perfetto trace-event JSON at
    ``out`` (default ``<directory>/trace.json``) and return its path.

    Layout: one track (pid) per rank, named ``rank R``; paired
    ``span_begin``/``span_end`` events become nested B/E duration events
    per thread (only COMPLETED spans are emitted, so every B has a
    matching E), complete-form ``span`` events become "X" slices (ts +
    dur — written at exit, so a synthesized pair could mis-order on a µs
    tie; X slices cannot be imbalanced); collectives become per-rank "X"
    complete events chained across ranks by flow events
    (``s``/``t``/``f`` sharing an id per occurrence of each op), so the
    gang-wide shape of an allreduce is one connected arrow in the
    Perfetto UI.  Monotonic span stamps align to the shared wall timeline
    via each rank's ``clock_anchor`` offset.

    Request tracing (docs/OBSERVABILITY.md §Request tracing): serving
    spans whose args carry a ``trace_id`` and whose name is a
    cross-process hop anchor (the Router's ``serve_dispatch``, the
    replica's ``serve_handle``) are chained by per-trace flow events —
    one connected arrow from the router's dispatch slice into the
    replica's request tree, exactly like the collective flows but keyed
    on the trace id instead of the occurrence index (two DIFFERENT
    processes, not the same op on every rank).  Returns None when no
    rank stream exists."""
    directory = directory or _state.dir
    if not directory:
        return None
    flush()  # this process's own stream must include the latest events
    trace: List[dict] = []
    coll_occurrence: Dict[Any, int] = {}  # op -> running flow id per rank
    # trace_id -> [(ts_mid, pid, tid, stream_idx)] of its hop-anchor
    # slices across ALL streams; becomes one flow chain per request
    req_flow: Dict[str, List[tuple]] = {}
    flow_anchors = ("serve_dispatch", "serve_handle")
    any_events = False
    for rank_id, path in _iter_rank_files(directory):
        events = _load_rank_events(path)
        if not events:
            continue
        any_events = True
        # supervised restarts APPEND to the same rank file, so one stream
        # can hold several perf_counter epochs; a single whole-stream
        # offset would shift one epoch's spans by the inter-process-start
        # delta.  Track the NEAREST PRECEDING anchor in file order
        # instead: anchors are re-emitted per flush, so every epoch's
        # events follow an anchor of their own epoch.
        anchor_offs = [float(e["wall"]) - float(e["mono"]) for e in events
                       if e.get("kind") == "clock_anchor"
                       and "wall" in e and "mono" in e]
        offset = (anchor_offs[0] if anchor_offs
                  else _mono_offset(events, rank_id))
        trace.append({"ph": "M", "name": "process_name", "pid": rank_id,
                      "tid": 0, "args": {"name": f"rank {rank_id}"}})
        open_spans: Dict[Any, dict] = {}
        tids: Dict[Any, int] = {}
        n_coll: Dict[str, int] = {}
        def span_args(begin: dict) -> dict:
            args = {k: v for k, v in begin.items()
                    if k not in ("t", "kind", "rank", "name", "span",
                                 "parent", "depth", "tid", "mono",
                                 "dur_ms")}
            args["span_id"] = begin.get("span")
            return args

        for idx, ev in enumerate(events):
            kind = ev.get("kind")
            if kind == "clock_anchor" and "wall" in ev and "mono" in ev:
                offset = float(ev["wall"]) - float(ev["mono"])
            elif kind == "span_begin" and "span" in ev:
                # remember the stream index: record() appends under one
                # lock, so file order IS true chronological order within
                # a rank — the only tiebreak that can never invert a
                # span's own B/E pair on a µs ts tie (depth-based keys
                # sorted a zero-width nested span's E before its B)
                ev["_idx"] = idx
                open_spans[ev["span"]] = ev
            elif kind == "span_end" and ev.get("span") in open_spans:
                # paired form -> B/E pair, each carrying its source
                # record's stream index so the stable ts sort below
                # reconstructs enter/exit order exactly on ties
                begin = open_spans.pop(ev["span"])
                begin_idx = begin.pop("_idx", idx)
                tid = tids.setdefault(begin.get("tid"), len(tids))
                ts0 = (float(begin["mono"]) + offset) * 1e6
                ts1 = (float(ev["mono"]) + offset) * 1e6
                trace.append({"ph": "B", "name": begin.get("name", "?"),
                              "pid": rank_id, "tid": tid,
                              "ts": ts0, "args": span_args(begin),
                              "_sub": begin_idx})
                trace.append({"ph": "E", "name": begin.get("name", "?"),
                              "pid": rank_id, "tid": tid,
                              "ts": max(ts1, ts0), "_sub": idx})
                if begin.get("trace_id") and \
                        begin.get("name") in flow_anchors:
                    req_flow.setdefault(str(begin["trace_id"]), []).append(
                        ((ts0 + max(ts1, ts0)) / 2.0, rank_id, tid,
                         begin_idx))
            elif kind == "span" and "mono" in ev:
                # complete form -> ph "X" (ts + dur).  These are written
                # at EXIT, so their file order is child-before-parent; a
                # synthesized B/E pair could land child-B-before-parent-B
                # on a µs tie and unbalance the track.  X events carry
                # their extent and cannot be imbalanced; Perfetto nests
                # them natively.
                tid = tids.setdefault(ev.get("tid"), len(tids))
                ts_x = (float(ev["mono"]) + offset) * 1e6
                dur_x = max(float(ev.get("dur_ms", 0.0)) * 1e3, 0.001)
                trace.append({"ph": "X", "name": ev.get("name", "?"),
                              "pid": rank_id, "tid": tid,
                              "ts": ts_x, "dur": dur_x,
                              "args": span_args(ev),
                              "_sub": idx})
                if ev.get("trace_id") and ev.get("name") in flow_anchors:
                    req_flow.setdefault(str(ev["trace_id"]), []).append(
                        (ts_x + dur_x / 2.0, rank_id, tid, idx))
            elif kind == "mem":
                # per-rank counter track: category bytes render as a
                # stacked area series under the span timeline (Perfetto
                # ph "C"); sampled off the hot path so ts is the wall
                # stamp, like collectives
                cats = ev.get("categories") or {}
                args = {}
                for cat, row in cats.items():
                    args[cat] = (row.get("nbytes", 0)
                                 if isinstance(row, dict) else row)
                if not args:
                    args = {"live_bytes": ev.get("live_bytes", 0)}
                trace.append({"ph": "C", "name": "memory", "pid": rank_id,
                              "tid": 0,
                              "ts": float(ev.get("t", 0.0)) * 1e6,
                              "args": args})
            elif kind == "collective":
                op = str(ev.get("op", "collective"))
                occ = n_coll.get(op, 0)
                n_coll[op] = occ + 1
                tid = tids.setdefault(None, len(tids))
                dur = max(float(ev.get("wall_ms", 0.0)) * 1e3, 1.0)
                # record_collective stamps the event AFTER the op, so its
                # wall stamp is the END; the slice starts wall_ms earlier
                ts = (float(ev.get("t", 0.0))
                      - float(ev.get("wall_ms", 0.0)) / 1e3) * 1e6
                trace.append({"ph": "X", "name": op, "pid": rank_id,
                              "tid": tid, "ts": ts, "dur": dur,
                              "args": {"nbytes": ev.get("nbytes"),
                                       "traced": ev.get("traced")}})
                # flow: the occ-th <op> on every rank is the same logical
                # collective; chain the ranks with one flow id
                flow_id = hash((op, occ)) & 0x7FFFFFFF
                first = coll_occurrence.setdefault((op, occ), rank_id)
                ph = "s" if first == rank_id else "t"
                trace.append({"ph": ph, "cat": "collective", "name": op,
                              "id": flow_id, "pid": rank_id, "tid": tid,
                              "ts": ts + dur / 2, "bp": "e"})
    # one flow chain per traced request: s on its earliest hop anchor
    # (the router's dispatch slice), t on each later one (the replica's
    # handle slice — two on a failover re-dispatch, still ONE chain)
    for trace_key, pts in req_flow.items():
        if len(pts) < 2:
            continue  # a single-process trace has nothing to link
        pts.sort()
        flow_id = hash(("rqtrace", trace_key)) & 0x7FFFFFFF
        for i, (ts_mid, pid_, tid_, sub) in enumerate(pts):
            trace.append({"ph": "s" if i == 0 else "t", "cat": "request",
                          "name": trace_key, "id": flow_id, "pid": pid_,
                          "tid": tid_, "ts": ts_mid, "bp": "e",
                          "_sub": sub})
    if not any_events:
        return None
    # chronological, with the _sub stream-index key breaking µs ts ties
    # (per-rank file order is true chronological order, so B/E nesting
    # and each pair's own B-before-E survive zero-width spans)
    meta = [e for e in trace if e["ph"] == "M"]
    rest = sorted((e for e in trace if e["ph"] != "M"),
                  key=lambda e: (e["ts"], e.get("_sub", 0)))
    if rest:
        t0 = min(e["ts"] for e in rest)
        for e in rest:
            e["ts"] = round(e["ts"] - t0, 3)
            e.pop("_sub", None)
    out = out or os.path.join(directory, "trace.json")
    # the supervisor's post-mortem re-export may target a directory no
    # rank ever created (SIGKILLed gang -> no atexit export ran)
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    payload = {"traceEvents": meta + rest, "displayTimeUnit": "ms"}
    tmp = f"{out}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, out)
    return out


def _prom_escape(value: str) -> str:
    return str(value).replace("\\", r"\\").replace('"', r'\"')


def render_prometheus(mode: str = "live") -> str:
    """Render this process's ``summary()`` + memwatch rollups as ONE
    OpenMetrics text exposition ending in ``# EOF`` — the single
    formatter shared by BOTH sinks: :func:`export_prometheus` (file
    snapshot, ``mode="atexit"``) and the live ``mxnet_tpu.metrics_server``
    ``/metrics`` endpoint (``mode="live"``).  Every render stamps
    ``mx_export_timestamp_seconds`` and ``mx_export_mode{mode=...}`` so a
    dashboard can tell a dead rank's last atexit snapshot from a live
    scrape.  Reads the recorder's locked rollups only: no jax, no device
    sync, safe from any thread at any time (including concurrently with
    a flush)."""
    s = summary()
    rank_lbl = f'rank="{s["rank"]}"'
    lines: List[str] = []

    def gauge(name, value, labels="", kind="gauge"):
        lines.append(f"# TYPE {name} {kind}")
        lbl = f"{{{rank_lbl}{',' if labels else ''}{labels}}}"
        lines.append(f"{name}{lbl} {value}")

    def per_key(name, rows, field, label_key, kind="counter", scale=1):
        lines.append(f"# TYPE {name} {kind}")
        for key, row in sorted(rows.items()):
            v = row[field] * scale if scale != 1 else row[field]
            lines.append(
                f'{name}{{{rank_lbl},{label_key}="{_prom_escape(key)}"}} '
                f"{v}")

    # export provenance first: a scraper (or the launch.py gang merge)
    # derives per-rank staleness from the timestamp, and the mode label
    # says whether these numbers are a live process or a final snapshot
    gauge("mx_export_timestamp_seconds", round(time.time(), 3))
    lines.append("# TYPE mx_export_mode gauge")
    lines.append(f'mx_export_mode{{{rank_lbl},'
                 f'mode="{_prom_escape(mode)}"}} 1')
    steps = s["steps"]
    per_key("mx_step_total", steps, "count", "executor")
    per_key("mx_step_compile_total", steps, "compile_count", "executor")
    per_key("mx_step_compile_ms_total", steps, "compile_ms", "executor")
    per_key("mx_step_exec_ms_total", steps, "exec_ms", "executor")
    per_key("mx_step_block_wait_ms_total", steps, "block_wait_ms",
            "executor")
    per_key("mx_step_transfer_bytes_total", steps, "transfer_bytes",
            "executor")
    lines.append("# TYPE mx_step_samples_per_sec gauge")
    for key, row in sorted(steps.items()):
        if "samples_per_sec" in row:
            lines.append(
                f'mx_step_samples_per_sec{{{rank_lbl},'
                f'executor="{_prom_escape(key)}"}} '
                f'{row["samples_per_sec"]}')
    c = s["collectives"]
    gauge("mx_collective_total", c["count"], kind="counter")
    gauge("mx_collective_bytes_total", c["bytes"], kind="counter")
    gauge("mx_collective_ms_total", c["total_ms"], kind="counter")
    if c["total_ms"] > 0:
        gauge("mx_collective_bytes_per_sec",
              round(c["bytes"] / (c["total_ms"] / 1e3), 1))
    ck = s["checkpoints"]
    gauge("mx_checkpoint_saves_total", ck["saves"], kind="counter")
    gauge("mx_checkpoint_save_ms_total", ck["save_ms"], kind="counter")
    gauge("mx_checkpoint_loads_total", ck["loads"], kind="counter")
    gauge("mx_checkpoint_fallbacks_total", ck["fallbacks"], kind="counter")
    sv = s["serving"]
    if sv["requests"] or sv["queue_depth"] or sv["active_slots"] \
            or sv.get("weight_swaps"):
        gauge("mx_serve_requests_total", sv["requests"], kind="counter")
        gauge("mx_serve_tokens_total", sv["tokens"], kind="counter")
        gauge("mx_serve_queue_wait_ms_total", sv["queue_wait_ms"],
              kind="counter")
        gauge("mx_serve_decode_ms_total", sv["decode_ms"], kind="counter")
        gauge("mx_serve_latency_p50_ms", sv["p50_latency_ms"])
        gauge("mx_serve_latency_p99_ms", sv["p99_latency_ms"])
        gauge("mx_serve_ttft_p50_ms", sv["p50_ttft_ms"])
        gauge("mx_serve_ttft_p99_ms", sv["p99_ttft_ms"])
        lines.append("# TYPE mx_serve_slo_violations_total counter")
        for stage in ("ttft", "tpot"):
            lines.append(
                f'mx_serve_slo_violations_total{{{rank_lbl},'
                f'stage="{stage}"}} {sv["slo_violations"][stage]}')
        gauge("mx_serve_queue_depth", sv["queue_depth"])
        gauge("mx_serve_active_slots", sv["active_slots"])
        # hot-swap generation gauge + applied-swap counter: which weight
        # set is serving, and how many flips it took to get there
        gauge("mx_serve_weight_generation",
              sv.get("weight_generation", 0))
        gauge("mx_serve_weight_swaps_total", sv.get("weight_swaps", 0),
              kind="counter")
        # info-style precision label (a NEW gauge, not a new label on
        # the existing series — label-set changes break scrapers)
        lines.append("# TYPE mx_serve_precision_info gauge")
        lines.append(
            f'mx_serve_precision_info{{{rank_lbl},'
            f'precision="{_prom_escape(sv.get("precision", "fp32"))}"}} 1')
        pc = sv.get("prefix_cache", {})
        if pc.get("hits") or pc.get("misses"):
            gauge("mx_serve_prefix_hits_total", pc["hits"], kind="counter")
            gauge("mx_serve_prefix_misses_total", pc["misses"],
                  kind="counter")
            gauge("mx_serve_prefix_tokens_reused_total",
                  pc["tokens_reused"], kind="counter")
            gauge("mx_serve_prefix_hit_rate", pc["hit_rate"])
        sp = sv.get("spec", {})
        if sp.get("rounds"):
            gauge("mx_serve_spec_rounds_total", sp["rounds"],
                  kind="counter")
            gauge("mx_serve_spec_proposed_total", sp["proposed"],
                  kind="counter")
            gauge("mx_serve_spec_accepted_total", sp["accepted"],
                  kind="counter")
            gauge("mx_serve_spec_accept_rate", sp["accept_rate"])
        # request-tracing cause attribution: per-cause counter + one
        # exemplar-style gauge per cause carrying the NEWEST trace id as
        # a label (bounded cardinality: one series per cause, the trace
        # id label rewrites in place — the poor-man's OpenMetrics
        # exemplar, since the text exposition has no native ones)
        causes = sv.get("causes", {})
        if causes:
            lines.append("# TYPE mx_serve_request_cause_total counter")
            for cause, n in sorted(causes.items()):
                lines.append(
                    f'mx_serve_request_cause_total{{{rank_lbl},'
                    f'cause="{_prom_escape(cause)}"}} {n}')
            ex = sv.get("cause_exemplars", {})
            if ex:
                lines.append(
                    "# TYPE mx_serve_request_exemplar_latency_ms gauge")
                for cause, row in sorted(ex.items()):
                    lines.append(
                        f'mx_serve_request_exemplar_latency_ms{{'
                        f'{rank_lbl},cause="{_prom_escape(cause)}",'
                        f'trace_id="{_prom_escape(row["trace_id"])}"}} '
                        f'{row["latency_ms"]}')
    per_key("mx_span_total", s["spans"], "count", "span", kind="counter")
    per_key("mx_span_ms_total", s["spans"], "total_ms", "span",
            kind="counter")
    per_key("mx_span_max_ms", s["spans"], "max_ms", "span", kind="gauge")
    lines.append("# TYPE mx_retrace_signatures gauge")
    for key, row in sorted(s["retraces"].items()):
        lines.append(
            f'mx_retrace_signatures{{{rank_lbl},'
            f'executor="{_prom_escape(key)}"}} {row["traces"]}')
    if _state.hb_wall:
        gauge("mx_heartbeat_age_seconds",
              round(max(0.0, time.time() - _state.hb_wall), 3))
    gauge("mx_restart_count", s["restart_count"])
    # memory watchdog gauges (docs/OBSERVABILITY.md §Memory): lazy import
    # — memwatch rides on this module, never the other way around
    try:
        from . import memwatch as _memwatch

        ms = _memwatch.summary()
        if ms["samples"]:
            gauge("mx_mem_samples_total", ms["samples"], kind="counter")
            gauge("mx_mem_watermark_bytes", ms["watermark_bytes"])
            lines.append("# TYPE mx_mem_category_bytes gauge")
            for cat, nb in sorted(ms["categories"].items()):
                lines.append(
                    f'mx_mem_category_bytes{{{rank_lbl},'
                    f'category="{_prom_escape(cat)}"}} {nb}')
            gauge("mx_mem_leak_detected",
                  1 if ms["leak"]["active"] else 0)
        if ms["compiles"]["count"]:
            gauge("mx_mem_compile_total", ms["compiles"]["count"],
                  kind="counter")
            gauge("mx_mem_compile_ms_total", ms["compiles"]["wall_ms"],
                  kind="counter")
            gauge("mx_mem_compile_cache_hits_total",
                  ms["compiles"].get("cache_hits", 0), kind="counter")
    except Exception:  # the exposition must land even if memwatch breaks
        pass
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def export_prometheus(path: Optional[str] = None) -> Optional[str]:
    """Write an OpenMetrics text snapshot (one :func:`render_prometheus`
    render, ``mode="atexit"``) to ``path`` (default ``<telemetry
    dir>/metrics-<rank>.prom``) and return the path — the file-sink half
    of the formatter: point a node exporter textfile collector at it.
    For pull-based scraping of a LIVE process use
    ``mxnet_tpu.metrics_server`` (MX_METRICS_PORT), which serves the
    same exposition with ``mode="live"``."""
    if path is None:
        if not _state.dir:
            return None
        path = os.path.join(_state.dir, f"metrics-{_state.rank}.prom")
    body = render_prometheus(mode="atexit")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)  # scrapers never see a torn snapshot
    return path


def _trace_export_target() -> Optional[str]:
    """MX_TRACE_EXPORT: unset/0/false = off (the default — exporting reads
    back every rank's stream, not something to pay unasked); 1/true =
    export into MX_TELEMETRY_DIR; any other value = target directory."""
    raw = os.environ.get("MX_TRACE_EXPORT", "").strip()
    if not raw or raw.lower() in ("0", "false", "off"):
        return None
    if raw.lower() in ("1", "true", "on"):
        return _state.dir
    return raw


def _export_at_exit() -> None:
    """Best-effort per-process export.  Rank 0's merge here can race peer
    ranks that are still running (their final flush lands after the
    read); under tools/launch.py the supervisor re-runs the merge after
    every rank is reaped and overwrites this trace.json with the
    authoritative one.  Unsupervised single-rank runs have no race."""
    target = _trace_export_target()
    if not target or not _state.dir:
        return
    try:
        os.makedirs(target, exist_ok=True)
        export_prometheus(
            os.path.join(target, f"metrics-{_state.rank}.prom"))
        # every rank snapshots its own metrics; only rank 0 merges the
        # gang trace (all ranks racing one trace.json would tear it)
        if _state.rank == 0:
            export_chrome_trace(_state.dir,
                                out=os.path.join(target, "trace.json"))
    except Exception as e:  # export must never turn a clean exit dirty
        _LOG.warning("MX_TRACE_EXPORT failed: %s", e)


# LIFO atexit: this runs BEFORE the flush registered above, so
# _export_at_exit's own flush() call covers the final pending events
atexit.register(_export_at_exit)


# attach the sink at import when the launcher/user exported the env
# (mxnet_tpu/__init__ imports this module; workers inherit the variable
# from tools/launch.py's environment pass-through)
enable()
