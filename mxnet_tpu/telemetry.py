"""Runtime telemetry: step metrics, retrace detection, heartbeats, and a
flight recorder (docs/OBSERVABILITY.md).

The reference MXNet answers "why is training slow / stuck?" with its
engine-level profiler brackets (src/profiler/); here whole steps fuse into
single XLA executables, so the observable unit is the *step*, not the op.
This module is the process-wide recorder every layer reports into:

  * step events from the compiled executors (``parallel/data_parallel.py``,
    ``symbol/executor.py``, the Gluon ``Trainer``): wall time, first-call
    compile vs steady-state execute, samples/sec, host<->device bytes;
  * **retrace detection**: every executor reports its jit call signature;
    when one executor accumulates more than ``MX_TELEMETRY_RETRACE_LIMIT``
    distinct signatures a rate-limited warning names the offending
    signature — the classic silent 10x slowdown of shape-churning input
    pipelines (each new shape forces a full XLA recompile);
  * collective events (op, nbytes, duration) from ``kvstore.py`` and
    ``parallel/dist.py``;
  * fault-tolerance lifecycle events (checkpoint save/load durations,
    digest fallbacks, rendezvous retries, restart count) from
    ``checkpoint.py`` / ``parallel/dist.py``;
  * **per-rank heartbeat files** (step + timestamp, atomically renamed)
    that the ``tools/launch.py`` supervisor polls to diagnose a hung rank
    *before* killing it.

Disabled (no ``MX_TELEMETRY_DIR``) the recorder no-ops: ``record*()`` and
``heartbeat()`` return immediately, so the hot step path pays only a
boolean check.  Retrace *detection* stays on — a microseconds-scale
signature build + set lookup per executor call — because the warning it
guards is precisely for runs nobody was watching closely enough to
enable telemetry on; ``MX_TELEMETRY_RETRACE_LIMIT=0`` switches it off
entirely (call sites check ``retrace_enabled()`` before building the
signature).

On-disk layout under ``MX_TELEMETRY_DIR`` (one stream per rank; the
filename patterns are mirrored in tools/launch.py, which must stay
importable without jax — keep them in sync)::

    rank-<R>.jsonl        append-only event stream, one JSON object/line:
                          {"t": <unix sec>, "kind": "...", "rank": R, ...}
    heartbeat-<R>.json    {"rank": R, "step": S, "time": <unix sec>,
                          "pid": P, "restart": K} — atomically replaced at
                          most every MX_HEARTBEAT_SEC seconds

Events buffer in memory (bounded) and a daemon thread flushes them every
``MX_TELEMETRY_FLUSH_SEC`` seconds; the last ``RING_SIZE`` events also live
in an in-process ring (the flight recorder) surfaced by ``summary()`` /
``flight_tail()``.
"""
from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["enabled", "enable", "disable", "record", "record_step",
           "record_collective", "record_fused_update", "record_block_wait",
           "heartbeat", "note_signature", "summary", "flight_tail", "flush",
           "reset", "rank", "event_path", "heartbeat_path", "RING_SIZE"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

# flight-recorder depth (in-process ring; the supervisor reads the JSONL
# file's tail instead, so this only bounds summary()/flight_tail())
RING_SIZE = 256
# force an inline flush when this many events are pending (bounds memory
# between flusher wakeups under event bursts)
_FLUSH_PENDING_MAX = 128
# distinct jit signatures one executor may accumulate before the retrace
# warning fires (override: MX_TELEMETRY_RETRACE_LIMIT)
_RETRACE_LIMIT_DEFAULT = 5


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def event_path(directory: str, rank_id: int) -> str:
    """Per-rank JSONL event stream path (mirrored in tools/launch.py)."""
    return os.path.join(directory, f"rank-{rank_id}.jsonl")


def heartbeat_path(directory: str, rank_id: int) -> str:
    """Per-rank heartbeat file path (mirrored in tools/launch.py)."""
    return os.path.join(directory, f"heartbeat-{rank_id}.json")


def rank() -> int:
    """This process's gang rank (0 for single-process runs)."""
    try:
        return int(os.environ.get("MX_PROC_ID",
                                  os.environ.get("DMLC_WORKER_ID", "0")))
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# recorder state
# ---------------------------------------------------------------------------
class _State:
    """All mutable recorder state in one bag so reset() is atomic."""

    def __init__(self):
        self.lock = threading.RLock()
        # serializes the actual file append: flush() may run concurrently
        # on the daemon flusher, an inline >=128-pending flush, and
        # atexit — interleaved write(2) calls would tear JSONL lines
        self.write_lock = threading.Lock()
        self.dir: Optional[str] = None
        self.rank: int = 0
        self.enabled = False
        self.ring: deque = deque(maxlen=RING_SIZE)
        self.pending: List[str] = []
        self.counts: Dict[str, int] = {}
        # executor -> {count, first_ms, total_ms, samples, bytes}
        self.steps: Dict[str, Dict[str, float]] = {}
        self.coll = {"count": 0, "bytes": 0, "total_ms": 0.0,
                     "compile_ms": 0.0}
        self.fused = {"count": 0, "n_params": 0, "n_buckets": 0,
                      "bytes": 0, "jitted_calls": 0}
        self.ckpt = {"saves": 0, "save_ms": 0.0, "save_bytes": 0,
                     "loads": 0, "load_ms": 0.0, "fallbacks": 0}
        # executor -> {"sigs": set, "traces": int, "warned_at": int,
        #              "last_sig": str}
        self.retraces: Dict[str, Dict[str, Any]] = {}
        self.flusher: Optional[threading.Thread] = None
        self.flush_sec = 1.0
        self.hb_interval = 5.0
        self.hb_last = 0.0
        self.hb_step = -1


_state = _State()


def enabled() -> bool:
    return _state.enabled


def enable(directory: Optional[str] = None) -> None:
    """Attach the JSONL sink (and heartbeats).  With no argument, reads
    ``MX_TELEMETRY_DIR``; a missing/empty directory leaves the recorder
    disabled.  Idempotent; safe to call from any thread."""
    directory = directory or os.environ.get("MX_TELEMETRY_DIR")
    if not directory:
        return
    with _state.lock:
        if _state.enabled and _state.dir == directory:
            return
        os.makedirs(directory, exist_ok=True)
        _state.dir = directory
        _state.rank = rank()
        _state.flush_sec = max(0.05, _env_float("MX_TELEMETRY_FLUSH_SEC", 1.0))
        _state.hb_interval = max(0.0, _env_float("MX_HEARTBEAT_SEC", 5.0))
        _state.enabled = True
        if _state.flusher is None:
            _state.flusher = threading.Thread(
                target=_flusher_loop, name="mx-telemetry-flush", daemon=True)
            _state.flusher.start()
    record("start", pid=os.getpid(),
           restart=int(os.environ.get("MX_RESTART_COUNT", "0") or 0))


def disable() -> None:
    """Detach the sink (pending events are flushed first)."""
    flush()
    with _state.lock:
        _state.enabled = False


def reset() -> None:
    """Drop all aggregates, ring contents, and retrace history (tests)."""
    global _state
    flush()
    with _state.lock:
        fl = _state.flusher
        _state = _State()
        _state.flusher = fl  # one flusher thread per process is plenty


def _flusher_loop() -> None:
    while True:
        time.sleep(_state.flush_sec)
        try:
            flush()
        except Exception:  # a full disk must not kill the training process
            pass


def flush() -> None:
    """Append pending events to this rank's JSONL file."""
    st = _state
    with st.lock:
        if not st.pending or st.dir is None:
            return
        lines, st.pending = st.pending, []
        path = event_path(st.dir, st.rank)
    with st.write_lock:  # whole-batch append; no mid-line interleaving
        try:
            with open(path, "a") as f:
                f.write("".join(lines))
        except OSError as e:
            _LOG.warning("telemetry flush to %s failed: %s", path, e)


atexit.register(flush)


# ---------------------------------------------------------------------------
# event recording
# ---------------------------------------------------------------------------
def record(kind: str, **fields) -> None:
    """Record one event.  No-op unless the recorder is enabled."""
    if not _state.enabled:
        return
    ev = {"t": round(time.time(), 4), "kind": kind, "rank": _state.rank}
    ev.update(fields)
    try:
        line = json.dumps(ev) + "\n"
    except (TypeError, ValueError):
        ev = {k: (v if isinstance(v, (int, float, str, bool, type(None)))
                  else str(v)) for k, v in ev.items()}
        line = json.dumps(ev) + "\n"
    with _state.lock:
        _state.counts[kind] = _state.counts.get(kind, 0) + 1
        _state.ring.append(ev)
        _state.pending.append(line)
        inline_flush = len(_state.pending) >= _FLUSH_PENDING_MAX
    if inline_flush:
        flush()


def record_step(executor: str, step: int, wall_s: float,
                samples: Optional[int] = None, transfer_bytes: int = 0,
                traced: bool = False, h2d_overlapped: int = 0,
                **fields) -> None:
    """One executor step.  ``traced=True`` marks a first-call/retrace step
    whose wall time includes trace+compile; those are aggregated separately
    so steady-state samples/sec is not polluted by compile time.

    ``wall_s`` is the python-side wall of the step call — the recorder
    deliberately does NOT block_until_ready (forcing a device sync per
    step would serialize the dispatch pipeline the observability layer is
    meant to leave undisturbed).  Under async dispatch a single step's
    wall is dispatch cost, not device time; over a sustained loop the
    dispatch queue backpressures and per-step walls converge to true step
    cadence, so the AGGREGATES (mean_exec_ms, samples_per_sec over many
    steps) are meaningful while the first few per-step numbers undercount.
    For exact per-program device times use mx.profiler (its timed_call
    blocks by design).

    ``h2d_overlapped`` counts the subset of ``transfer_bytes`` that a
    device prefetcher staged in the background (already resident when the
    step ran) — the async-pipeline overlap evidence.  Extra async fields
    travel via ``**fields``: ``inflight_depth`` (pending window depth
    after this dispatch) and ``block_wait_ms`` (time this dispatch spent
    blocked because the window was full)."""
    if not _state.enabled:
        return
    wall_ms = wall_s * 1e3
    with _state.lock:
        st = _state.steps.setdefault(executor, _new_step_agg())
        st["count"] += 1
        if traced:
            st["compile_count"] += 1
            st["compile_ms"] += wall_ms
        else:
            st["exec_ms"] += wall_ms
            if samples:
                st["samples"] += int(samples)
        st["bytes"] += int(transfer_bytes)
        st["overlap_bytes"] += int(h2d_overlapped)
    ev = dict(executor=executor, step=int(step), wall_ms=round(wall_ms, 3),
              traced=bool(traced), **fields)
    if samples is not None:
        ev["samples"] = int(samples)
        if wall_s > 0:
            ev["samples_per_sec"] = round(samples / wall_s, 2)
    if transfer_bytes:
        ev["transfer_bytes"] = int(transfer_bytes)
    if h2d_overlapped:
        ev["h2d_overlapped"] = int(h2d_overlapped)
    record("step", **ev)


def _new_step_agg() -> Dict[str, float]:
    return {"count": 0, "compile_count": 0, "compile_ms": 0.0,
            "exec_ms": 0.0, "samples": 0, "bytes": 0,
            "overlap_bytes": 0, "block_wait_ms": 0.0}


def record_block_wait(executor: str, wall_s: float) -> None:
    """Host time spent BLOCKED on the device for one executor: a forced
    readback (``AsyncLoss.wait``), a full in-flight window, or a fence
    sync.  Aggregate-only (no per-event line — a hot loop forces every
    step); ``summary()['steps'][executor]['block_wait_ms']`` is the
    rollup that shows how much wall time the host truly lost to the
    device, the before/after number for the async pipeline."""
    if not _state.enabled or wall_s <= 0:
        return
    with _state.lock:
        st = _state.steps.setdefault(executor, _new_step_agg())
        st["block_wait_ms"] += wall_s * 1e3


def record_collective(op: str, nbytes: int, wall_s: float,
                      traced: bool = False, **fields) -> None:
    """One collective (kvstore reduce, global allreduce, ...).

    ``traced=True`` marks a first-use call whose wall includes the jit
    trace + XLA compile of the collective program; it aggregates into
    ``compile_ms`` so comm cost is never conflated with compile cost."""
    if not _state.enabled:
        return
    with _state.lock:
        _state.coll["count"] += 1
        _state.coll["bytes"] += int(nbytes)
        if traced:
            _state.coll["compile_ms"] += wall_s * 1e3
        else:
            _state.coll["total_ms"] += wall_s * 1e3
    record("collective", op=op, nbytes=int(nbytes),
           wall_ms=round(wall_s * 1e3, 3), traced=bool(traced), **fields)


def record_fused_update(n_params: int, n_buckets: int, nbytes: int,
                        n_jitted_calls: int, **fields) -> None:
    """One fused optimizer step (docs/PERFORMANCE.md): how many params
    updated, through how many gradient buckets and jitted update calls —
    the before/after evidence that the O(n_params) dispatch storm
    collapsed to O(1).  Aggregated under ``summary()['fused_update']``."""
    if not _state.enabled:
        return
    with _state.lock:
        f = _state.fused
        f["count"] += 1
        f["n_params"] += int(n_params)
        f["n_buckets"] += int(n_buckets)
        f["bytes"] += int(nbytes)
        f["jitted_calls"] += int(n_jitted_calls)
    record("fused_update", n_params=int(n_params), n_buckets=int(n_buckets),
           nbytes=int(nbytes), n_jitted_calls=int(n_jitted_calls), **fields)


def record_checkpoint(event: str, step: int, wall_s: float = 0.0,
                      nbytes: int = 0, **fields) -> None:
    """Checkpoint lifecycle: event in {save, load, fallback}."""
    if not _state.enabled:
        return
    with _state.lock:
        c = _state.ckpt
        if event == "save":
            c["saves"] += 1
            c["save_ms"] += wall_s * 1e3
            c["save_bytes"] += int(nbytes)
        elif event == "load":
            c["loads"] += 1
            c["load_ms"] += wall_s * 1e3
        elif event == "fallback":
            c["fallbacks"] += 1
    ev = dict(step=int(step), **fields)
    if wall_s:
        ev["wall_ms"] = round(wall_s * 1e3, 3)
    if nbytes:
        ev["nbytes"] = int(nbytes)
    record(f"checkpoint_{event}", **ev)


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------
def heartbeat(step: int, force: bool = False) -> None:
    """Write this rank's heartbeat file (atomic rename), rate-limited to
    one write per ``MX_HEARTBEAT_SEC``.  No-op when telemetry is disabled.

    The reported step is MONOTONIC (max over all reports): several layers
    heartbeat with their own counters — e.g. after a supervised restart
    the restored AsyncCheckpointer reports the global step while a fresh
    Trainer counts from 1 — and the supervisor's "last heartbeat at step
    S" diagnosis must not flap between them."""
    if not _state.enabled or _state.dir is None:
        return
    now = time.monotonic()
    with _state.lock:
        if not force and _state.hb_last and \
                now - _state.hb_last < _state.hb_interval:
            return
        _state.hb_last = now
        step = _state.hb_step = max(int(step), _state.hb_step)
        directory, rank_id = _state.dir, _state.rank
    payload = {"rank": rank_id, "step": int(step),
               "time": round(time.time(), 3), "pid": os.getpid(),
               "restart": int(os.environ.get("MX_RESTART_COUNT", "0") or 0)}
    path = heartbeat_path(directory, rank_id)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # readers never see a torn heartbeat
    except OSError as e:
        _LOG.warning("heartbeat write to %s failed: %s", path, e)


# ---------------------------------------------------------------------------
# retrace detection
# ---------------------------------------------------------------------------
def _retrace_limit() -> int:
    try:
        return int(os.environ.get("MX_TELEMETRY_RETRACE_LIMIT",
                                  _RETRACE_LIMIT_DEFAULT))
    except (TypeError, ValueError):
        return _RETRACE_LIMIT_DEFAULT


def retrace_enabled() -> bool:
    """Retrace detection runs by default (even without a telemetry sink —
    it exists for runs nobody instrumented); ``MX_TELEMETRY_RETRACE_LIMIT=0``
    is the kill switch for hot loops where even the per-call signature
    build must go."""
    return _retrace_limit() > 0


# an executor name past this many registry entries folds into one shared
# overflow bucket: a script that builds a fresh executor per batch must
# not grow the registry forever — and since each such instance contributes
# its (distinct-shaped) first signature to the SAME bucket, the storm the
# per-instance keys would hide is detected there instead
_RETRACE_REGISTRY_MAX = 1024
_OVERFLOW_KEY = "<executor-churn-overflow>"


def note_signature(executor: str, signature) -> bool:
    """Report one executor call's jit signature (shapes/dtypes/static args).

    Returns True when the signature is NEW for this executor — i.e. jax.jit
    will trace and XLA will compile on this call.  When an executor
    accumulates more than the retrace limit of distinct signatures, emits a
    rate-limited warning naming the newest signature (then again only each
    time the count doubles — a storm logs a handful of lines, not one per
    step)."""
    if not retrace_enabled():
        return False
    with _state.lock:
        if (executor not in _state.retraces
                and len(_state.retraces) >= _RETRACE_REGISTRY_MAX):
            executor = _OVERFLOW_KEY
        ent = _state.retraces.setdefault(
            executor, {"sigs": set(), "traces": 0, "warned_at": 0,
                       "last_sig": ""})
        if signature in ent["sigs"]:
            return False
        if len(ent["sigs"]) >= 4096:
            # bounded memory even in a storm: evict one (arbitrary) stored
            # signature rather than dropping the NEW one — a pipeline that
            # churns past the cap and then stabilizes must find its final
            # signature in the set, not be re-counted as a fresh trace
            # (and re-warned) on every remaining step of the run
            ent["sigs"].pop()
        ent["sigs"].add(signature)
        ent["traces"] += 1
        # truncate at store time: summary() embeds last_sig verbatim into
        # bench records and dumps() output — a multi-KB feed signature
        # must not ride along whole
        ent["last_sig"] = str(signature)[:400]
        n = ent["traces"]
        limit = _retrace_limit()
        warn = n > limit and (ent["warned_at"] == 0
                              or n >= 2 * ent["warned_at"])
        if warn:
            ent["warned_at"] = n
    if warn:
        _LOG.warning(
            "executor %s has traced %d distinct signatures (retrace limit "
            "%d); newest: %s.  Every new input shape/dtype forces a full "
            "XLA recompile — the classic silent 10x slowdown.  Pad or "
            "bucket inputs to stable shapes (see docs/OBSERVABILITY.md).",
            executor, n, limit, str(signature)[:400])
        record("retrace", executor=executor, traces=n,
               signature=str(signature)[:400])
    return True


# ---------------------------------------------------------------------------
# rollups
# ---------------------------------------------------------------------------
def flight_tail(k: int = 20) -> List[dict]:
    """The last k events recorded in this process (newest last)."""
    with _state.lock:
        return list(_state.ring)[-k:]


def summary() -> dict:
    """JSON-serializable rollup of everything recorded so far.  Works even
    when the recorder is disabled (retrace tracking is always on)."""
    with _state.lock:
        steps = {}
        for name, st in _state.steps.items():
            exec_count = st["count"] - st["compile_count"]
            row = {
                "count": st["count"],
                "compile_count": st["compile_count"],
                "compile_ms": round(st["compile_ms"], 3),
                "exec_ms": round(st["exec_ms"], 3),
                "transfer_bytes": st["bytes"],
                "h2d_overlapped_bytes": st.get("overlap_bytes", 0),
                "block_wait_ms": round(st.get("block_wait_ms", 0.0), 3),
            }
            if exec_count > 0:
                row["mean_exec_ms"] = round(st["exec_ms"] / exec_count, 3)
            if st["samples"] and st["exec_ms"] > 0:
                row["samples_per_sec"] = round(
                    st["samples"] / (st["exec_ms"] / 1e3), 2)
            steps[name] = row
        retraces = {
            name: {"traces": ent["traces"], "last_signature": ent["last_sig"]}
            for name, ent in _state.retraces.items()
        }
        out = {
            "enabled": _state.enabled,
            "rank": _state.rank if _state.enabled else rank(),
            "dir": _state.dir,
            "events": dict(_state.counts),
            "steps": steps,
            "collectives": {
                "count": _state.coll["count"],
                "bytes": _state.coll["bytes"],
                "total_ms": round(_state.coll["total_ms"], 3),
                "compile_ms": round(_state.coll["compile_ms"], 3),
            },
            "checkpoints": {k: (round(v, 3) if isinstance(v, float) else v)
                            for k, v in _state.ckpt.items()},
            "fused_update": dict(_state.fused),
            "retraces": retraces,
            "restart_count": int(
                os.environ.get("MX_RESTART_COUNT", "0") or 0),
        }
    return out


# attach the sink at import when the launcher/user exported the env
# (mxnet_tpu/__init__ imports this module; workers inherit the variable
# from tools/launch.py's environment pass-through)
enable()
