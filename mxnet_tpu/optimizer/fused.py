"""Fused optimizer apply: ONE jitted XLA call updates every dense parameter.

The per-param ``Updater`` dispatches one jitted update kernel per parameter
per step — for ResNet-50 that is ~160 tiny XLA dispatches of pure host-side
overhead (the kernels themselves are microseconds).  This module collapses
the whole optimizer tail into a single executable per (optimizer class,
hyperparam signature): weights/grads/states flatten into pytrees and the
entire update runs as one ``jax.jit`` call with donated weight+state
buffers, the fusion argument of TVM (arXiv:1802.04799) and Tensor
Processing Primitives (arXiv:2104.05755) applied to the optimizer step.

Design rules keeping parity with the per-param path exact:

  * the fused kernels ARE the registered per-param ops
    (``ops/optimizer_ops.py``) — same formulas, traced once over all
    params instead of jitted once per param, so fp32 results are
    bit-identical;
  * per-step scalars (lr after schedule/mults, wd, rescale_grad, Adam's
    bias-corrected lr) enter as TRACED arguments — a scheduler changing
    lr every step never retraces; structural hypers (momentum on/off,
    clip_gradient, centered) are static and key the executable cache;
  * state layout is the per-param ``Updater``'s own ``states`` dict
    (this class subclasses it), so save/load_states, the sparse
    fallback, and the ``MX_FUSED_UPDATE=0`` kill switch all see one
    state representation;
  * anything the fused path cannot express — row_sparse grads, unknown
    optimizer classes, mismatched weight/grad devices, exotic state
    shapes — falls back to the per-param update for JUST those params.

Multi-precision (bf16/fp16 weight + fp32 master in the state) fuses too:
the master updates in fp32 and the low-precision weight is one cast, as
in the ``mp_*`` reference ops.

``MX_FUSED_UPDATE=0`` disables the whole path (``get_updater`` then
returns the plain per-param ``Updater``).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import aot_cache
from .. import engine
from .. import memwatch
from .. import telemetry
from .optimizer import Optimizer, Updater

__all__ = ["FusedUpdater", "fused_enabled"]


def fused_enabled() -> bool:
    """MX_FUSED_UPDATE kill switch (default: on)."""
    return os.environ.get("MX_FUSED_UPDATE", "1").lower() not in (
        "0", "false", "off")


# ---------------------------------------------------------------------------
# per-optimizer fused specs
#
# A spec answers three questions for its optimizer class:
#   static(opt)            -> hashable structural hypers (executable key)
#   kind(opt, w, state)    -> per-param update variant, or None (fall back)
#   scalars(opt, index)    -> per-step traced scalars for this param
#   apply(static, kind, w, g, s, sc, rescale) -> (new_w, new_state)
# `apply` runs INSIDE the jit trace; it must only branch on static/kind.
# ---------------------------------------------------------------------------
_SPECS: Dict[str, type] = {}


def _register_spec(cls):
    _SPECS[cls.opt_name] = cls
    return cls


_ND_CLASSES = None  # (NDArray, BaseSparseNDArray), resolved on first use —
# lazy like the rest of the optimizer package (circular-import order), but
# cached because kind() probes run per param per step


def _nd_classes():
    global _ND_CLASSES
    if _ND_CLASSES is None:
        from ..ndarray import NDArray
        from ..ndarray.sparse import BaseSparseNDArray

        _ND_CLASSES = (NDArray, BaseSparseNDArray)
    return _ND_CLASSES


def _is_nd(x) -> bool:
    dense, sparse = _nd_classes()
    return isinstance(x, dense) and not isinstance(x, sparse)


def _clip(opt) -> float:
    return float(opt.clip_gradient) if opt.clip_gradient is not None else -1.0


@_register_spec
class _SGDSpec:
    opt_name = "SGD"

    @staticmethod
    def static(opt):
        return (float(opt.momentum), _clip(opt))

    @staticmethod
    def kind(opt, weight, state):
        if state is None:
            return "plain"
        if _is_nd(state):
            return "mom"
        if (isinstance(state, tuple) and len(state) == 2
                and _is_nd(state[0]) and state[0].shape == weight.shape):
            if state[1] is None:
                return "mp"
            if _is_nd(state[1]):
                return "mp_mom"
        return None

    @staticmethod
    def scalars(opt, index):
        return (opt._get_lr(index), opt._get_wd(index))

    @staticmethod
    def apply(static, kind, w, g, s, sc, rescale):
        from ..ops import optimizer_ops as oo

        momentum, clip = static
        lr, wd = sc
        kw = dict(lr=lr, wd=wd, rescale_grad=rescale, clip_gradient=clip)
        if kind == "plain":
            return oo.sgd_update(w, g, **kw), None
        if kind == "mom":
            return oo.sgd_mom_update(w, g, s, momentum=momentum, **kw)
        if kind == "mp":
            nw, n32 = oo.mp_sgd_update(w, g, s[0], **kw)
            return nw, (n32, None)
        nw, nm, n32 = oo.mp_sgd_mom_update(w, g, s[1], s[0],
                                           momentum=momentum, **kw)
        return nw, (n32, nm)


@_register_spec
class _AdamSpec:
    opt_name = "Adam"

    @staticmethod
    def static(opt):
        return (float(opt.beta1), float(opt.beta2), float(opt.epsilon),
                _clip(opt))

    @staticmethod
    def kind(opt, weight, state):
        if not (isinstance(state, tuple) and len(state) == 2):
            return None
        mp_shape = getattr(state[0], "shape", None) == weight.shape
        if opt.multi_precision and _is_nd(state[0]) and mp_shape \
                and isinstance(state[1], tuple) and len(state[1]) == 2 \
                and all(_is_nd(x) for x in state[1]):
            return "mp"
        if opt.multi_precision and mp_shape:
            # the generic base-class mp path would engage (and, for fp32
            # weights, misread (mean, var) as (master, state)) — keep that
            # exact per-param behavior instead of guessing
            return None
        if all(_is_nd(x) for x in state):
            return "plain"
        return None

    @staticmethod
    def scalars(opt, index):
        import math

        t = opt._index_update_count[index]
        # bias correction folded into lr, exactly as Adam.update does
        lr = opt._get_lr(index) * math.sqrt(1.0 - opt.beta2 ** t) \
            / (1.0 - opt.beta1 ** t)
        return (lr, opt._get_wd(index))

    @staticmethod
    def apply(static, kind, w, g, s, sc, rescale):
        from ..ops import optimizer_ops as oo

        beta1, beta2, eps, clip = static
        lr, wd = sc
        kw = dict(lr=lr, beta1=beta1, beta2=beta2, epsilon=eps, wd=wd,
                  rescale_grad=rescale, clip_gradient=clip)
        if kind == "plain":
            mean, var = s
            nw, nmean, nvar = oo.adam_update(w, g, mean, var, **kw)
            return nw, (nmean, nvar)
        master, (mean, var) = s
        n32, nmean, nvar = oo.adam_update(master, g, mean, var, **kw)
        return n32.astype(w.dtype), (n32, (nmean, nvar))


@_register_spec
class _RMSPropSpec:
    opt_name = "RMSProp"

    @staticmethod
    def static(opt):
        cw = float(opt.clip_weights) if opt.clip_weights is not None else -1.0
        return (float(opt.gamma1), float(opt.gamma2), float(opt.epsilon),
                _clip(opt), cw)

    @staticmethod
    def kind(opt, weight, state):
        if _is_nd(state):
            return "plain"
        if isinstance(state, tuple) and len(state) == 3 \
                and all(_is_nd(x) for x in state):
            return "centered"
        if (opt.multi_precision and isinstance(state, tuple)
                and len(state) == 2 and _is_nd(state[0])
                and state[0].shape == weight.shape):
            if _is_nd(state[1]):
                return "mp_plain"
            if isinstance(state[1], tuple) and len(state[1]) == 3 \
                    and all(_is_nd(x) for x in state[1]):
                return "mp_centered"
        return None

    @staticmethod
    def scalars(opt, index):
        return (opt._get_lr(index), opt._get_wd(index))

    @staticmethod
    def apply(static, kind, w, g, s, sc, rescale):
        from ..ops import optimizer_ops as oo

        gamma1, gamma2, eps, clip, cw = static
        lr, wd = sc
        kw = dict(lr=lr, wd=wd, rescale_grad=rescale, clip_gradient=clip,
                  epsilon=eps, clip_weights=cw)
        if kind == "plain":
            nw, nn = oo.rmsprop_update(w, g, s, gamma1=gamma1, **kw)
            return nw, nn
        if kind == "centered":
            n, g_buf, delta = s
            nw, nn, ng, nd_ = oo.rmspropalex_update(
                w, g, n, g_buf, delta, gamma1=gamma1, gamma2=gamma2, **kw)
            return nw, (nn, ng, nd_)
        master, inner = s
        if kind == "mp_plain":
            n32, nn = oo.rmsprop_update(master, g, inner, gamma1=gamma1, **kw)
            return n32.astype(w.dtype), (n32, nn)
        n, g_buf, delta = inner
        n32, nn, ng, nd_ = oo.rmspropalex_update(
            master, g, n, g_buf, delta, gamma1=gamma1, gamma2=gamma2, **kw)
        return n32.astype(w.dtype), (n32, (nn, ng, nd_))


# ---------------------------------------------------------------------------
# state pytree <-> NDArray structure
# ---------------------------------------------------------------------------
def _state_arrays(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_arrays(x) for x in s)
    return s._data


def _write_state(s, new):
    if s is None:
        return
    if isinstance(s, tuple):
        for x, nx in zip(s, new):
            _write_state(x, nx)
        return
    s._set_data(new)


def _flat_state_arrays(updater):
    """memwatch provider: every optimizer-state buffer this updater owns
    (momenta, Adam moments, fp32 masters), flattened out of the per-param
    state tuples — the "optimizer" slice of the live-array census."""
    out = []

    def walk(s):
        if s is None:
            return
        if isinstance(s, tuple):
            for x in s:
                walk(x)
            return
        data = getattr(s, "_data", None)
        if data is not None:
            out.append(data)

    for s in updater.states.values():
        walk(s)
    return out


class FusedUpdater(Updater):
    """Per-param-compatible updater with a fused ``apply([...])`` fast path.

    ``__call__`` is the inherited per-param update (kvstore per-key pushes,
    sparse grads).  ``apply(entries)`` — entries being ``(index, grad,
    weight)`` triples — partitions the batch into fused-eligible params
    (dense, known optimizer, recognized state layout) and per-param
    fallbacks, then updates every fused param in ONE jitted call per
    device.  ``last_info`` records what the most recent ``apply`` did.
    """

    def __init__(self, optimizer: Optimizer):
        super().__init__(optimizer)
        self._fn_cache: Dict[Any, Any] = {}
        # persistent AOT executables (MX_EXECUTABLE_CACHE_DIR), keyed by
        # the fn-cache key PLUS the group's weight shapes; False =
        # resolution failed, stay on the plain jit path
        self._aot_execs: Dict[Any, Any] = {}
        self.last_info: Optional[Dict[str, int]] = None
        # live-array census: the states dict is the "optimizer" category
        memwatch.register("optimizer", self, _flat_state_arrays)

    # -- fused executable cache -------------------------------------------
    def _jitted(self, spec, static, kinds, donate):
        key = (spec.opt_name, static, kinds, donate)
        fn = self._fn_cache.get(key)
        if fn is None:
            import jax

            apply_one = spec.apply

            def fused_fn(ws, gs, ss, scalars, rescale):
                # scalars is ONE stacked (n_params, k) array — python-float
                # leaves would force jax's slow dispatch path (a host->device
                # convert per scalar per step); unstacking happens at trace
                # time, so the executable sees plain f32 scalars
                new_ws: List = []
                new_ss: List = []
                for i, (kind, w, g, s) in enumerate(zip(kinds, ws, gs, ss)):
                    nw, ns = apply_one(static, kind, w, g, s,
                                       tuple(scalars[i]), rescale)
                    new_ws.append(nw)
                    new_ss.append(ns)
                return tuple(new_ws), tuple(new_ss)

            # mxlint: disable=retrace-hazard — cached in _fn_cache per
            # (optimizer, static hypers, kinds, donate); built once per key
            fn = jax.jit(fused_fn,
                         donate_argnums=(0, 2) if donate else ())
            self._fn_cache[key] = fn
        return fn

    # -- batch apply -------------------------------------------------------
    def apply(self, entries, donate: bool = False) -> Dict[str, int]:
        """Update a batch of ``(index, grad, weight)`` triples.

        Fused-eligible params update in one jitted call per distinct
        device; the rest take the per-param path.  ``donate=True`` donates
        the weight/state buffers to XLA on non-CPU backends (the caller
        asserts nothing else reads the old buffers — true for Trainer-owned
        parameters, NOT for kvstore-stored values aliased by pulls).
        Returns (and stores in ``last_info``) the dispatch accounting.
        """
        with telemetry.span("fused_apply", n_params=len(entries)):
            return self._apply_impl(entries, donate)

    def _apply_impl(self, entries, donate: bool) -> Dict[str, int]:
        _dense, sparse_cls = _nd_classes()
        opt = self.optimizer
        spec = _SPECS.get(type(opt).__name__)
        fused: Dict[Any, List] = {}  # ctx -> [(index, g, w, state, kind)]
        fallback: List = []
        for index, grad, weight in entries:
            state = self._ensure_state(index, weight)
            kind = None
            if (spec is not None
                    and not isinstance(grad, sparse_cls)
                    and not isinstance(weight, sparse_cls)
                    and grad.context == weight.context):
                kind = spec.kind(opt, weight, state)
            if kind is None:
                fallback.append((index, grad, weight))
            else:
                fused.setdefault(weight.context, []).append(
                    (index, grad, weight, state, kind))
        info = {"n_params": len(entries), "n_fused": 0, "n_fallback": 0,
                "n_jitted_calls": 0, "nbytes": 0}
        for ctx, group in fused.items():
            info["nbytes"] += self._apply_group(spec, group, ctx, donate)
            info["n_jitted_calls"] += 1
            info["n_fused"] += len(group)
        for index, grad, weight in fallback:
            opt.update_multi_precision(index, weight, grad,
                                       self.states[index])
            info["n_fallback"] += 1
        self.last_info = info
        return info

    def _apply_group(self, spec, group, ctx, donate) -> int:
        opt = self.optimizer
        for index, _g, _w, _s, _k in group:
            opt._update_count(index)
        kinds = tuple(kind for *_x, kind in group)
        static = spec.static(opt)
        donate = bool(donate) and ctx.jax_device.platform != "cpu"
        # cold = this (optimizer, hypers, kinds, donate) executable is
        # about to be built: the first call below pays trace + XLA
        # compile and is booked as ONE compile event (never re-emitted)
        cold = (spec.opt_name, static, kinds, donate) not in self._fn_cache
        fn = self._jitted(spec, static, kinds, donate)
        ws = tuple(w._data for _i, _g, w, _s, _k in group)
        gs = tuple(g._data for _i, g, _w, _s, _k in group)
        ss = tuple(_state_arrays(s) for _i, _g, _w, s, _k in group)
        scalars = np.asarray([spec.scalars(opt, index)
                              for index, _g, _w, _s, _k in group],
                             dtype=np.float32)
        rescale = np.float32(opt.rescale_grad)
        shapes = tuple((tuple(w.shape), str(w.dtype)) for w in ws)
        parts = ("FusedUpdater", spec.opt_name, static, kinds, donate,
                 shapes)
        run, cache_info = fn, {}
        t0 = time.perf_counter() if cold else 0.0
        aot_key = (spec.opt_name, static, kinds, donate, shapes)
        if aot_cache.enabled():
            # persistent AOT executable: a restarted process deserializes
            # the fused-apply program instead of tracing + recompiling it
            cached = self._aot_execs.get(aot_key)
            if cached is None:
                cached, cache_info = aot_cache.get_or_compile(
                    fn, (ws, gs, ss, scalars, rescale),
                    fingerprint=memwatch.fingerprint(parts),
                    platform=ctx.jax_device.platform,
                    device_ids=(int(ctx.jax_device.id),))
                self._aot_execs[aot_key] = (cached if cached is not None
                                            else False)
            if cached is not None and cached is not False:
                run = cached
        new_ws, new_ss = run(ws, gs, ss, scalars, rescale)
        if cold:
            memwatch.note_compile(
                f"FusedUpdater:{spec.opt_name}", parts,
                wall_s=time.perf_counter() - t0, site="fused",
                # a deserialized executable never traced fused_fn — skip
                # the analysis retrace, the cache facts carry the story
                jitted=None if cache_info.get("cache_hit") else fn,
                args=memwatch.shape_structs((ws, gs, ss, scalars,
                                             rescale)),
                n_params=len(group), **cache_info)
        if engine.is_naive():
            import jax

            # mxlint: disable=hot-sync — MXNET_ENGINE_TYPE=NaiveEngine
            # CONTRACT: synchronous per-op dispatch, sync is the feature
            jax.block_until_ready(new_ws)
        nbytes = 0
        for (index, _g, w, s, _k), nw, ns in zip(group, new_ws, new_ss):
            nbytes += nw.nbytes
            w._set_data(nw)
            _write_state(s, ns)
        return nbytes
