"""Optimizer package (reference: python/mxnet/optimizer/)."""
from .optimizer import *
from .optimizer import Optimizer, Updater, get_updater, register, create
from . import lr_scheduler
