"""Optimizer package (reference: python/mxnet/optimizer/)."""
from .optimizer import *
from .optimizer import Optimizer, Updater, get_updater, register, create
from .fused import FusedUpdater, fused_enabled
from . import lr_scheduler
