"""Optimizers.

Reference parity: python/mxnet/optimizer/optimizer.py (Optimizer registry
~L40, SGD w/ momentum + multi-precision ~L700, Adam, LAMB, AdaGrad, RMSProp,
Updater/get_updater ~L1700) dispatching to the fused update ops in
ops/optimizer_ops.py (reference src/operator/optimizer_op.*).

Multi-precision: bf16/fp16 weights keep an fp32 master copy in the state,
updated by the mp_* fused ops — the TPU-normal bf16 training recipe.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from ..base import MXNetError
from ..ops import registry as _reg

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "Adamax", "Nadam", "AdaGrad",
           "AdaDelta", "RMSProp", "Ftrl", "Signum", "LAMB", "Updater",
           "get_updater", "register", "create"]

_REGISTRY: Dict[str, type] = {}


def register(cls):
    name = cls.__name__.lower()
    _REGISTRY[name] = cls
    return cls


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    try:
        return _REGISTRY[name.lower()](**kwargs)
    except KeyError:
        raise MXNetError(f"unknown optimizer {name!r}") from None


class Optimizer:
    """Base optimizer (reference ~L40)."""

    opt_registry = _REGISTRY

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.multi_precision = multi_precision
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult: Dict[str, float] = {}
        self.wd_mult: Dict[str, float] = {}

    # -- registry-style API -------------------------------------------------
    @staticmethod
    def register(cls):
        return register(cls)

    @staticmethod
    def create_optimizer(name, **kwargs):
        return create(name, **kwargs)

    # -- bookkeeping --------------------------------------------------------
    def _update_count(self, index) -> None:
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index) -> float:
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr: float) -> None:
        if self.lr_scheduler is not None:
            raise MXNetError(
                "LRScheduler of the optimizer has already been defined.")
        self.lr = lr

    @property
    def learning_rate(self) -> float:
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr: float):
        self.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult: Dict) -> None:
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict) -> None:
        self.wd_mult = dict(args_wd_mult)

    # -- state --------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (np.float16,) or (
                self.multi_precision and np.dtype(weight._data.dtype).name
                in ("float16", "bfloat16")):
            from ..ndarray import NDArray

            master = NDArray(weight._data.astype(np.float32),
                             ctx=weight.context)
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and isinstance(state, tuple) and len(state) == 2 \
                and getattr(state[0], "shape", None) == weight.shape:
            self._update_mp(index, weight, grad, state)
        else:
            self.update(index, weight, grad, state)

    def _update_mp(self, index, weight, grad, state):
        # generic master-weight path: update master fp32 copy, cast down
        master, inner = state
        self.update(index, master, grad, inner)
        weight._set_data(master._data.astype(weight._data.dtype))

    def _common_kwargs(self, index) -> Dict[str, Any]:
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        kw["clip_gradient"] = (self.clip_gradient
                               if self.clip_gradient is not None else -1.0)
        return kw


def _zeros_like(weight):
    import jax.numpy as jnp

    from ..ndarray import NDArray

    return NDArray(jnp.zeros_like(weight._data), ctx=weight.context)


def _zeros_like32(weight):
    import jax.numpy as jnp

    from ..ndarray import NDArray

    return NDArray(jnp.zeros(weight.shape, jnp.float32), ctx=weight.context)


# ---------------------------------------------------------------------------
# row_sparse lazy updates (reference: src/operator/optimizer_op.cc
# SGDUpdateRspImpl / SGDMomLazyUpdateRspImpl / AdamUpdateRspImpl — only the
# rows present in the sparse gradient are read or written; untouched rows
# see neither weight decay nor momentum decay).
#
# Gradients are pre-aggregated EAGERLY (sparse.aggregate_rows: host-side
# unique -> true row count, no padding) before entering the jitted kernels,
# so each kernel may assume unique scatter targets.
# ---------------------------------------------------------------------------
_rs_kernels: Dict[str, Any] = {}


def _rs_grad(grad):
    """(unique_ids, f32 values) from a RowSparseNDArray gradient."""
    import jax.numpy as jnp

    from ..ndarray.sparse import aggregate_rows

    uids, vals = aggregate_rows(grad._aux["indices"], grad._data)
    return uids, vals.astype(jnp.float32)


def _get_rs_kernel(name: str):
    kernel = _rs_kernels.get(name)
    if kernel is not None:
        return kernel
    import jax
    import jax.numpy as jnp

    def prep(g, rows_w, wd, rescale, clip):
        g = g * rescale
        g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
        return g + wd * rows_w

    if name == "sgd":
        def kernel(w, uids, g, lr, wd, rescale, clip):
            rows_w = w[uids].astype(jnp.float32)
            g = prep(g, rows_w, wd, rescale, clip)
            return w.at[uids].add((-lr * g).astype(w.dtype))
    elif name == "sgd_mom":
        def kernel(w, m, uids, g, lr, momentum, wd, rescale, clip):
            rows_w = w[uids].astype(jnp.float32)
            rows_m = m[uids]
            g = prep(g, rows_w, wd, rescale, clip)
            new_m = momentum * rows_m - lr * g
            return (w.at[uids].add(new_m.astype(w.dtype)),
                    m.at[uids].set(new_m))
    elif name == "adam":
        def kernel(w, mean, var, uids, g, lr, b1, b2, eps, wd, rescale,
                   clip):
            rows_w = w[uids].astype(jnp.float32)
            g = prep(g, rows_w, wd, rescale, clip)
            new_mean = b1 * mean[uids] + (1 - b1) * g
            new_var = b2 * var[uids] + (1 - b2) * jnp.square(g)
            step = lr * new_mean / (jnp.sqrt(new_var) + eps)
            return (w.at[uids].add((-step).astype(w.dtype)),
                    mean.at[uids].set(new_mean),
                    var.at[uids].set(new_var))
    else:
        raise MXNetError(f"no row_sparse kernel {name!r}")
    kernel = jax.jit(kernel)
    _rs_kernels[name] = kernel
    return kernel


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision (reference ~L700)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _zeros_like32(weight)
        return None

    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray

        self._update_count(index)
        kw = self._common_kwargs(index)
        if isinstance(grad, RowSparseNDArray):
            uids, vals = _rs_grad(grad)
            if state is None:
                new_w = _get_rs_kernel("sgd")(
                    weight._data, uids, vals, kw["lr"], kw["wd"],
                    kw["rescale_grad"], kw["clip_gradient"])
                weight._set_data(new_w)
            else:
                new_w, new_m = _get_rs_kernel("sgd_mom")(
                    weight._data, state._data, uids, vals, kw["lr"],
                    self.momentum, kw["wd"], kw["rescale_grad"],
                    kw["clip_gradient"])
                weight._set_data(new_w)
                state._set_data(new_m)
            return
        if state is None:
            _reg.invoke_by_name("sgd_update", [weight, grad], out=weight, **kw)
        else:
            new_w, new_mom = _reg.invoke_by_name(
                "sgd_mom_update", [weight, grad, state],
                momentum=self.momentum, **kw)
            weight._set_data(new_w._data)
            state._set_data(new_mom._data)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = isinstance(state, tuple) and len(state) == 2 and \
            getattr(state[0], "shape", None) == weight.shape
        if not use_mp:
            return self.update(index, weight, grad, state)
        master, mom = state
        self._update_count(index)
        kw = self._common_kwargs(index)
        if mom is None:
            new_w, new32 = _reg.invoke_by_name(
                "mp_sgd_update", [weight, grad, master], **kw)
            weight._set_data(new_w._data)
            master._set_data(new32._data)
        else:
            new_w, new_mom, new32 = _reg.invoke_by_name(
                "mp_sgd_mom_update", [weight, grad, mom, master],
                momentum=self.momentum, **kw)
            weight._set_data(new_w._data)
            mom._set_data(new_mom._data)
            master._set_data(new32._data)

    def create_state_multi_precision(self, index, weight):
        name = np.dtype(weight._data.dtype).name
        if self.multi_precision and name in ("float16", "bfloat16"):
            from ..ndarray import NDArray

            master = NDArray(weight._data.astype(np.float32), ctx=weight.context)
            mom = _zeros_like32(weight) if self.momentum != 0.0 else None
            return (master, mom)
        return self.create_state(index, weight)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return _zeros_like32(weight) if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            _reg.invoke_by_name("sgd_update", [weight, grad], out=weight, **kw)
        else:
            new_w, new_mom = _reg.invoke_by_name(
                "nag_mom_update", [weight, grad, state],
                momentum=self.momentum, **kw)
            weight._set_data(new_w._data)
            state._set_data(new_mom._data)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like32(weight), _zeros_like32(weight))

    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray

        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        # bias correction folded into lr (reference optimizer.py Adam.update)
        coef1 = 1.0 - self.beta1**t
        coef2 = 1.0 - self.beta2**t
        kw["lr"] *= math.sqrt(coef2) / coef1
        mean, var = state
        if isinstance(grad, RowSparseNDArray):
            uids, vals = _rs_grad(grad)
            new_w, new_mean, new_var = _get_rs_kernel("adam")(
                weight._data, mean._data, var._data, uids, vals,
                kw["lr"], self.beta1, self.beta2, self.epsilon,
                kw["wd"], kw["rescale_grad"], kw["clip_gradient"])
        else:
            out = _reg.invoke_by_name(
                "adam_update", [weight, grad, mean, var], beta1=self.beta1,
                beta2=self.beta2, epsilon=self.epsilon, **kw)
            new_w, new_mean, new_var = (x._data for x in out)
        weight._set_data(new_w)
        mean._set_data(new_mean)
        var._set_data(new_var)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (_zeros_like32(weight), _zeros_like32(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1**t)
        wd = self._get_wd(index)
        mean, u = state

        def fn(w, g, m, v):
            g32 = g.astype(jnp.float32) * self.rescale_grad
            if self.clip_gradient is not None:
                g32 = jnp.clip(g32, -self.clip_gradient, self.clip_gradient)
            g32 = g32 + wd * w.astype(jnp.float32)
            new_m = self.beta1 * m + (1 - self.beta1) * g32
            new_u = jnp.maximum(self.beta2 * v, jnp.abs(g32))
            new_w = w.astype(jnp.float32) - lr * new_m / (new_u + 1e-8)
            return new_w.astype(w.dtype), new_m, new_u

        new_w, new_m, new_u = _reg.invoke_fn(fn, [weight, grad, mean, u])
        weight._set_data(new_w._data)
        mean._set_data(new_m._data)
        u._set_data(new_u._data)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (_zeros_like32(weight), _zeros_like32(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96**(t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96**((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        mean, var = state

        def fn(w, g, m, v):
            g32 = g.astype(jnp.float32) * self.rescale_grad
            if self.clip_gradient is not None:
                g32 = jnp.clip(g32, -self.clip_gradient, self.clip_gradient)
            g32 = g32 + wd * w.astype(jnp.float32)
            g_prime = g32 / (1.0 - self.m_schedule)
            new_m = self.beta1 * m + (1.0 - self.beta1) * g32
            m_prime = new_m / (1.0 - m_schedule_next)
            new_v = self.beta2 * v + (1.0 - self.beta2) * jnp.square(g32)
            v_prime = new_v / (1.0 - self.beta2**t)
            m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
            new_w = w.astype(jnp.float32) - lr * m_bar / (
                jnp.sqrt(v_prime) + self.epsilon)
            return new_w.astype(w.dtype), new_m, new_v

        new_w, new_m, new_v = _reg.invoke_fn(fn, [weight, grad, mean, var])
        weight._set_data(new_w._data)
        mean._set_data(new_m._data)
        var._set_data(new_v._data)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like32(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        new_w, new_hist = _reg.invoke_by_name(
            "adagrad_update", [weight, grad, state],
            epsilon=self.float_stable_eps, **kw)
        weight._set_data(new_w._data)
        state._set_data(new_hist._data)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like32(weight), _zeros_like32(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        acc_g, acc_delta = state
        kw = self._common_kwargs(index)
        kw.pop("lr")
        new_w, new_g, new_d = _reg.invoke_by_name(
            "adadelta_update", [weight, grad, acc_g, acc_delta], rho=self.rho,
            epsilon=self.epsilon, **kw)
        weight._set_data(new_w._data)
        acc_g._set_data(new_g._data)
        acc_delta._set_data(new_d._data)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like32(weight), _zeros_like32(weight),
                    _zeros_like32(weight))
        return _zeros_like32(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        cw = self.clip_weights if self.clip_weights is not None else -1.0
        if self.centered:
            n, g_buf, delta = state
            new_w, new_n, new_g, new_d = _reg.invoke_by_name(
                "rmspropalex_update", [weight, grad, n, g_buf, delta],
                gamma1=self.gamma1, gamma2=self.gamma2, epsilon=self.epsilon,
                clip_weights=cw, **kw)
            weight._set_data(new_w._data)
            n._set_data(new_n._data)
            g_buf._set_data(new_g._data)
            delta._set_data(new_d._data)
        else:
            new_w, new_n = _reg.invoke_by_name(
                "rmsprop_update", [weight, grad, state], gamma1=self.gamma1,
                epsilon=self.epsilon, clip_weights=cw, **kw)
            weight._set_data(new_w._data)
            state._set_data(new_n._data)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_zeros_like32(weight), _zeros_like32(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        kw = self._common_kwargs(index)
        new_w, new_z, new_n = _reg.invoke_by_name(
            "ftrl_update", [weight, grad, z, n], lamda1=self.lamda1,
            beta=self.beta, **kw)
        weight._set_data(new_w._data)
        z._set_data(new_z._data)
        n._set_data(new_n._data)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return _zeros_like32(weight) if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            new_w = _reg.invoke_by_name("signsgd_update", [weight, grad], **kw)
            weight._set_data(new_w._data)
        else:
            new_w, new_mom = _reg.invoke_by_name(
                "signum_update", [weight, grad, state], momentum=self.momentum,
                wd_lh=self.wd_lh, **kw)
            weight._set_data(new_w._data)
            state._set_data(new_mom._data)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (reference: optimizer.py
    LAMB; phases map to lamb_update_phase1/2 fused ops)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_zeros_like32(weight), _zeros_like32(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        kw = self._common_kwargs(index)
        lr = kw.pop("lr")
        wd = kw.pop("wd")
        g_update, new_mean, new_var = _reg.invoke_by_name(
            "lamb_update_phase1", [weight, grad, mean, var], beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, t=t,
            bias_correction=self.bias_correction, wd=wd, **kw)
        r1 = _reg.invoke_fn(
            lambda w: jnp.linalg.norm(w.astype(jnp.float32)).reshape(1),
            [weight])
        r2 = _reg.invoke_fn(
            lambda g: jnp.linalg.norm(g).reshape(1), [g_update])
        new_w = _reg.invoke_by_name(
            "lamb_update_phase2", [weight, g_update, r1, r2], lr=lr,
            lower_bound=self.lower_bound if self.lower_bound is not None else -1.0,
            upper_bound=self.upper_bound if self.upper_bound is not None else -1.0)
        weight._set_data(new_w._data)
        mean._set_data(new_mean._data)
        var._set_data(new_var._data)


class Updater:
    """KVStore server-side updater (reference: get_updater ~L1700)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}

    def _ensure_state(self, index, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        elif not self.states_synced.get(index, True):
            # state loaded via set_states before this index was ever updated:
            # materialize device state and fill it from the numpy snapshot
            # (reference: Updater sync on first use)
            snapshot = self.states[index]
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            _numpy_to_states(self.states[index], snapshot)
            self.states_synced[index] = True
        return self.states[index]

    def __call__(self, index, grad, weight):
        state = self._ensure_state(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad, state)

    def get_states(self, dump_optimizer=False):
        import pickle

        state = {}
        for idx, s in self.states.items():
            state[idx] = _states_to_numpy(s)
        # update counts ride along: Adam/LAMB bias correction depends on
        # the per-index step count, so resume must not reset it (the
        # reference loses this without dump_optimizer — a documented
        # resume gap this build closes)
        payload = {"__states__": state,
                   "__counts__": dict(self.optimizer._index_update_count),
                   "__num_update__": self.optimizer.num_update}
        return pickle.dumps((payload, self.optimizer) if dump_optimizer
                            else payload)

    def set_states(self, states):
        import pickle

        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 2 and isinstance(
                data[1], Optimizer):
            payload, self.optimizer = data
        else:
            payload = data
        if isinstance(payload, dict) and "__states__" in payload:
            state = payload["__states__"]
            self.optimizer._index_update_count.update(
                payload.get("__counts__", {}))
            self.optimizer.num_update = max(self.optimizer.num_update,
                                            payload.get("__num_update__", 0))
        else:  # legacy payload: bare state dict
            state = payload
        self._numpy_states = state
        for idx, snp in state.items():
            if idx in self.states:
                _numpy_to_states(self.states[idx], snp)
            else:
                self.states[idx] = snp
                self.states_synced[idx] = False


def _states_to_numpy(s):
    from ..ndarray import NDArray

    if s is None:
        return None
    if isinstance(s, NDArray):
        return s.asnumpy()
    if isinstance(s, (list, tuple)):
        return tuple(_states_to_numpy(x) for x in s)
    return s


def _numpy_to_states(s, snp):
    import jax

    from ..ndarray import NDArray

    if s is None or snp is None:
        return
    if isinstance(s, NDArray):
        s._set_data(jax.device_put(snp.astype(np.dtype(s._data.dtype)),
                                   s.context.jax_device))
        return
    if isinstance(s, (list, tuple)):
        for x, xnp in zip(s, snp):
            _numpy_to_states(x, xnp)


def get_updater(optimizer: Optimizer) -> Updater:
    """The kvstore/Trainer updater for `optimizer`: the fused batch updater
    unless MX_FUSED_UPDATE=0 pins the per-param path (docs/PERFORMANCE.md)."""
    from .fused import FusedUpdater, fused_enabled

    if fused_enabled():
        return FusedUpdater(optimizer)
    return Updater(optimizer)
