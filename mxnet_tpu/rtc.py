"""``mx.rtc`` — runtime kernel compilation (DESCOPED on TPU).

Reference: src/common/rtc.cc (`mx.rtc.CudaModule` compiles CUDA source via
NVRTC / hipRTC at runtime).  There is no CUDA-source path on TPU and XLA
is already a runtime compiler; the sanctioned runtime-kernel mechanism in
this framework is Pallas (``mxnet_tpu.ops.pallas`` — see
ops/pallas/flash_attention.py for the pattern).  Every entry point here
raises with that pointer rather than silently not existing.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel"]

_MSG = ("mx.rtc is descoped on TPU: there is no CUDA-source runtime "
        "compilation path.  Write runtime kernels in Pallas instead "
        "(mxnet_tpu.ops.pallas; ops/pallas/flash_attention.py is the "
        "worked example), or rely on XLA fusion which compiles the "
        "traced graph at runtime already.")


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)


class CudaKernel:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)
