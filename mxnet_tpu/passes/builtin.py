"""The built-in pass catalog (docs/PRECISION.md §Pass pipeline).

Absorbs the PR 15 one-offs as registered passes — ``amp`` wraps the
graph-level cast policy, ``quant_int8`` the calibrated serving rewrite —
with UNCHANGED behavior (each pass's scope is the exact precision scope
the module globals drove, so the traced programs are bitwise identical
to the pre-pipeline path), and adds the two new ones this layer
unlocked:

  * ``quant_int4`` — weight-only int4 serving (precision/quantize.py's
    int4 path): packed weights + group-wise scales dequantize in-trace;
  * ``fused_kernels`` — substitute registered Pallas kernels
    (ops/pallas/registry.py) for their op-class at the dispatch point.

Pipeline factories live here too: :func:`pipeline_for_training` (built
from a Plan's PrecisionConfig + MX_PALLAS_FUSED) and
:func:`pipeline_for_serving` (adapter-contributed passes + fused), both
subject to MX_PASSES toggles.

Import discipline: this module sits under ``passes/__init__`` on the
package import spine — precision/pallas imports stay inside methods.
"""
from __future__ import annotations

import os
from typing import Iterable, Optional, Tuple

from ..base import MXNetError
from . import hooks
from .pipeline import (GraphPass, PassPipeline, apply_env_toggles,
                       register_pass_type)

__all__ = ["AmpPass", "QuantizeInt8Pass", "QuantizeInt4Pass",
           "FusedKernelPass", "fused_kernels_from_env",
           "pipeline_for_training", "pipeline_for_serving"]


# ---------------------------------------------------------------------------
# amp
# ---------------------------------------------------------------------------
@register_pass_type
class AmpPass(GraphPass):
    """Graph-level AMP as a pipeline pass: low-class ops trace with
    policy-dtype inputs, widen-class ops with f32, block outputs widen
    at the boundary (``precision/amp_pass.apply_amp`` — the one copy of
    that lowering)."""

    name = "amp"

    def __init__(self, policy, enabled: bool = True):
        super().__init__(enabled=enabled)
        if policy is None:
            raise MXNetError("AmpPass: policy must be an AmpPolicy (a "
                             "policy-less pass is just absent — don't add "
                             "it to the pipeline)")
        self.policy = policy

    def signature(self) -> Tuple:
        return self.policy.signature()

    def scope(self):
        from ..precision.runtime import amp_scope

        return amp_scope(self.policy)

    def wrap_apply(self, apply_fn):
        from ..precision.amp_pass import apply_amp

        return apply_amp(apply_fn, self.policy)

    def metadata(self) -> dict:
        # the backward-graph seam (docs/PRECISION.md §Pass pipeline): a
        # forward op traced with cast inputs yields a jax.vjp backward in
        # the SAME dtypes — these are the facts a future quantized-grads
        # pass keys off, published here so it has a home (no behavior
        # rides on this dict)
        return {"backward": {
            "grad_dtype": self.policy.dtype,
            "low": list(self.policy.low),
            "widen": list(self.policy.widen),
            "note": "vjp of a low-class op computes its input/param "
                    "cotangents in the policy dtype; widen-class "
                    "cotangents stay f32; the loss gradient seed is f32 "
                    "(boundary widen)"}}

    def config_json(self) -> dict:
        return {"policy": self.policy.to_json()}

    @classmethod
    def from_config(cls, rec: dict) -> "AmpPass":
        from ..precision.config import AmpPolicy

        return cls(AmpPolicy.from_json(rec.get("policy") or {}))


# ---------------------------------------------------------------------------
# quantization (int8 calibrated / int4 weight-only)
# ---------------------------------------------------------------------------
class _QuantPassBase(GraphPass):
    """Shared shape of the serving quant passes: a {id(layer): twin}
    entries map activated via ``runtime.quant_scope`` (the gluon
    Dense/Conv ``hybrid_forward`` consults it — the op-CLASS substitution
    happens at the layer seam, not the dispatch point), plus a
    restart-stable per-layer signature.

    ``from_config`` rebuilds a DESCRIPTOR pass: same signature (so
    fingerprints round-trip through checkpoint layout JSON), but no
    entries — entering its scope raises, because twins hold device
    buffers only the live model can produce."""

    def __init__(self, entries, layer_sig: Tuple, enabled: bool = True):
        super().__init__(enabled=enabled)
        self._entries = entries
        self._layer_sig = tuple(layer_sig)

    def scope(self):
        if self._entries is None:
            raise MXNetError(
                f"{self.name}: descriptor-only pass (rebuilt from JSON) "
                "cannot activate — quantized twins hold device buffers; "
                "re-quantize the live adapter instead")
        from ..precision.runtime import quant_scope

        return quant_scope(self._entries)


@register_pass_type
class QuantizeInt8Pass(_QuantPassBase):
    """Calibrated int8 serving rewrite (PR 15) as a pipeline pass: the
    scope maps Dense/Conv layers onto their calibrated int8 twins inside
    the adapter's traced prefill/decode bodies."""

    name = "quant_int8"

    def __init__(self, entries, calib_mode: str, layer_sig: Tuple,
                 enabled: bool = True):
        super().__init__(entries, layer_sig, enabled=enabled)
        self.calib_mode = calib_mode

    def signature(self) -> Tuple:
        return ("int8", self.calib_mode, self._layer_sig)

    def config_json(self) -> dict:
        return {"calib_mode": self.calib_mode,
                "layers": [list(e) for e in self._layer_sig]}

    @classmethod
    def from_config(cls, rec: dict) -> "QuantizeInt8Pass":
        return cls(None, rec.get("calib_mode", "naive"),
                   tuple(tuple(e) for e in rec.get("layers", ())))


@register_pass_type
class QuantizeInt4Pass(_QuantPassBase):
    """Weight-only int4 serving rewrite: Dense/Conv weights packed 2 per
    byte with group-wise scales (MX_QUANT_GROUP), dequantized IN-TRACE
    inside the engine's prefill/decode bodies (precision/quantize.py int4
    path) — ~0.15x weight bytes, the decode-bandwidth win."""

    name = "quant_int4"

    def __init__(self, entries, group_size: int, layer_sig: Tuple,
                 enabled: bool = True):
        super().__init__(entries, layer_sig, enabled=enabled)
        self.group_size = int(group_size)

    def signature(self) -> Tuple:
        return ("int4", self.group_size, self._layer_sig)

    def config_json(self) -> dict:
        return {"group_size": self.group_size,
                "layers": [list(e) for e in self._layer_sig]}

    @classmethod
    def from_config(cls, rec: dict) -> "QuantizeInt4Pass":
        return cls(None, int(rec.get("group_size", 32)),
                   tuple(tuple(e) for e in rec.get("layers", ())))


# ---------------------------------------------------------------------------
# fused kernels
# ---------------------------------------------------------------------------
@register_pass_type
class FusedKernelPass(GraphPass, hooks.OpHook):
    """Substitute registered Pallas kernels for their op-class at the
    dispatch point (ops/pallas/registry.py, the TPP-style registry —
    arXiv:2104.05755).  The pass IS its own dispatch hook: the traced
    branch of ``_invoke_impl`` asks ``substitute(op_name, attrs)`` and
    swaps the op's FCompute when the registry carries a kernel for the
    op-class on the platform the trace targets.  Off (disabled or not in
    the pipeline) the dispatch path is untouched — bitwise the
    pre-pipeline program."""

    name = "fused_kernels"

    def __init__(self, ops: Optional[Iterable[str]] = None,
                 enabled: bool = True):
        super().__init__(enabled=enabled)
        # None = every registered kernel; a tuple restricts the set (and
        # is fingerprint identity either way, resolved at construction
        # so later registry growth can't silently change a live program)
        if ops is None:
            from ..ops.pallas import registry as kreg

            ops = kreg.registered_ops()
        self._ops = tuple(sorted(ops))

    def signature(self) -> Tuple:
        return ("fused", self._ops)

    def scope(self):
        return hooks.op_hook(self)

    def substitute(self, op_name, attrs):
        if op_name not in self._ops:
            return None
        from ..ops.pallas import registry as kreg

        return kreg.substitution(op_name)

    def config_json(self) -> dict:
        return {"ops": list(self._ops)}

    @classmethod
    def from_config(cls, rec: dict) -> "FusedKernelPass":
        ops = rec.get("ops")
        return cls(ops=tuple(ops) if ops is not None else None)


def fused_kernels_from_env(environ=None) -> Optional[FusedKernelPass]:
    """MX_PALLAS_FUSED: 'auto' (default) substitutes only where the
    kernels compile natively (TPU, and MXNET_USE_FUSION on); '1' forces
    the pass (interpret-mode kernels — the CPU test path); '0' pins the
    stock op implementations (the bitwise-parity path)."""
    environ = environ if environ is not None else os.environ
    raw = (environ.get("MX_PALLAS_FUSED") or "auto").strip().lower()
    if raw in ("0", "false", "off"):
        return None
    if raw in ("1", "true", "on"):
        return FusedKernelPass()
    if raw != "auto":
        raise MXNetError(
            f"MX_PALLAS_FUSED={raw!r}: expected auto, 1/on, or 0/off")
    from ..ops import pallas

    return FusedKernelPass() if (pallas.enabled() and pallas.use_compiled()) \
        else None


# ---------------------------------------------------------------------------
# pipeline factories
# ---------------------------------------------------------------------------
def pipeline_for_training(precision, environ=None) -> PassPipeline:
    """The pipeline ``DataParallelStep._build`` applies around the one
    traced step: the Plan's AMP policy (when set) then fused-kernel
    substitution (when MX_PALLAS_FUSED resolves on).  With neither, the
    pipeline is empty and ``wrap_apply`` is identity — the exact
    pre-pipeline program."""
    passes = []
    if precision is not None and precision.amp is not None:
        passes.append(AmpPass(precision.amp))
    fused = fused_kernels_from_env(environ)
    if fused is not None:
        passes.append(fused)
    return apply_env_toggles(PassPipeline(passes), environ)


def pipeline_for_serving(adapter, environ=None) -> PassPipeline:
    """The serving engine's pipeline: adapter-contributed passes (a
    quantized adapter exposes its quant pass via ``.passes``) then
    fused-kernel substitution.  The engine enters this scope around its
    traced decode/prefill bodies and feeds ``signature()`` into its AOT
    fingerprint."""
    passes = list(getattr(adapter, "passes", ()) or ())
    fused = fused_kernels_from_env(environ)
    if fused is not None:
        passes.append(fused)
    return apply_env_toggles(PassPipeline(passes), environ)
