"""Ordered, composable, fingerprinted graph passes (docs/PRECISION.md
§Pass pipeline).

PR 15 proved the single dispatch point (``ops/registry._invoke_impl``)
can rewrite the whole traced graph — but AMP and int8 quant were each a
one-off module global: they could not be ordered, composed, or
fingerprinted together.  This module makes graph rewriting first-class,
the Relay pass-manager model (arXiv:1810.00952) shrunk to this repo's
trace-time reality:

  * a :class:`GraphPass` is a named, individually-toggleable rewrite
    whose effect is a trace-time scope (``scope()``) plus a structural
    ``signature()``;
  * a :class:`PassPipeline` is an ORDERED list of passes with ONE shared
    ``signature()`` that joins ``_fingerprint_parts``/the AOT executable
    cache — any pass config, toggle, or ORDER change produces a
    different fingerprint, so a restart under a different pass config
    misses instead of deserializing the wrong program;
  * a disabled pass is bitwise absent: it contributes nothing to the
    signature and nothing to the trace (``wrap_apply``/``scope`` skip
    it), so pipeline-with-pass-disabled traces a byte-identical program
    to the pre-pipeline path.

Pass classes register by name (:func:`register_pass_type`); an unknown
name raises naming the registered set.  The pipeline serializes to JSON
(name + config per pass, order preserved) and rides checkpoint layouts
next to the Plan.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Tuple

from ..base import MXNetError

__all__ = ["GraphPass", "PassPipeline", "register_pass_type",
           "available_passes", "resolve_pass_type", "apply_env_toggles"]

_PASS_TYPES: Dict[str, type] = {}


def register_pass_type(cls):
    """Class decorator: register ``cls`` under its ``name`` attribute so
    ``PassPipeline.from_json`` / MX_PASSES can resolve it."""
    name = getattr(cls, "name", None)
    if not name:
        raise MXNetError("register_pass_type: pass class needs a non-empty "
                         "'name' attribute")
    if name in _PASS_TYPES and _PASS_TYPES[name] is not cls:
        raise MXNetError(f"graph pass {name!r} registered twice")
    _PASS_TYPES[name] = cls
    return cls


def available_passes() -> List[str]:
    return sorted(_PASS_TYPES)


def resolve_pass_type(name: str) -> type:
    try:
        return _PASS_TYPES[name]
    except KeyError:
        raise MXNetError(
            f"unknown graph pass {name!r}: registered passes are "
            f"{available_passes()}") from None


class GraphPass:
    """One named graph rewrite.  Subclasses set ``name`` (the registry
    key) and override ``signature``/``scope`` (and optionally
    ``wrap_apply``, ``metadata``, ``config_json``/``from_config``)."""

    name: str = ""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)

    # -- identity ------------------------------------------------------
    def signature(self) -> Tuple:
        """Structural identity of this pass's CONFIG (hashable, restart-
        stable — the fingerprint contract).  The pipeline prefixes the
        pass name, so configs need not repeat it."""
        return ()

    # -- trace-time effect ---------------------------------------------
    def scope(self):
        """Context manager activating the pass's trace-time effect
        (dispatch hooks / precision scopes).  Default: no effect."""
        return contextlib.nullcontext()

    def wrap_apply(self, apply_fn):
        """Wrap a block-apply ``fn(params, key, *inputs)`` so its trace
        runs under this pass.  Default: enter ``scope()`` around the
        call — passes with boundary behavior (AMP's f32 widen) override."""
        scope = self.scope

        def passed_apply(params, key, *inputs):
            with scope():
                return apply_fn(params, key, *inputs)

        return passed_apply

    # -- seams ---------------------------------------------------------
    def metadata(self) -> dict:
        """Declarative facts downstream passes may consult (e.g. the AMP
        pass publishes its backward-graph cast decisions here so a future
        quantized-grads pass has a home).  Never affects the traced
        program or the fingerprint."""
        return {}

    # -- serialization -------------------------------------------------
    def config_json(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, rec: dict) -> "GraphPass":
        return cls()

    def __repr__(self):
        state = "on" if self.enabled else "off"
        return f"<GraphPass {self.name} {state}>"


class PassPipeline:
    """An ordered list of :class:`GraphPass` objects with one shared
    fingerprint.  Construct with passes in APPLICATION order: pass i's
    rewrite sees the graph produced under passes 0..i-1's scopes."""

    def __init__(self, passes=()):
        self.passes: List[GraphPass] = list(passes)
        seen = set()
        for p in self.passes:
            if not isinstance(p, GraphPass):
                raise MXNetError(
                    f"PassPipeline: {p!r} is not a GraphPass")
            if p.name in seen:
                raise MXNetError(
                    f"PassPipeline: duplicate pass {p.name!r} — a pipeline "
                    "holds each named pass at most once")
            seen.add(p.name)

    # -- access / toggling ---------------------------------------------
    def enabled(self) -> List[GraphPass]:
        return [p for p in self.passes if p.enabled]

    def names(self) -> List[str]:
        return [p.name for p in self.passes]

    def get(self, name: str) -> GraphPass:
        for p in self.passes:
            if p.name == name:
                return p
        raise MXNetError(
            f"PassPipeline: no pass named {name!r} in this pipeline "
            f"(has {self.names()}); registered passes are "
            f"{available_passes()}")

    def set_enabled(self, name: str, enabled: bool) -> "PassPipeline":
        self.get(name).enabled = bool(enabled)
        return self

    # -- identity -------------------------------------------------------
    def signature(self) -> Tuple:
        """ONE shared structural identity: (name, config) of every
        ENABLED pass, in order.  Joins the executable fingerprints
        (``DataParallelStep._fingerprint_parts`` hyper_sig, the serving
        engine fingerprint, the ``plan`` telemetry event) — order,
        toggle and config changes all split the fingerprint; a disabled
        pass is absent exactly as the pre-pipeline path was."""
        return ("passes",) + tuple(
            (p.name,) + tuple(p.signature()) for p in self.enabled())

    def fingerprint(self) -> str:
        from .. import memwatch

        return memwatch.fingerprint(self.signature())

    def metadata(self) -> dict:
        return {p.name: p.metadata() for p in self.passes}

    # -- trace-time application ----------------------------------------
    @contextlib.contextmanager
    def scope(self):
        """Enter every enabled pass's scope, pipeline order outermost-
        first.  With nothing enabled this is a no-op (the bitwise-off
        guarantee)."""
        with contextlib.ExitStack() as stack:
            for p in self.enabled():
                stack.enter_context(p.scope())
            yield

    def wrap_apply(self, apply_fn):
        """Wrap a block apply under every enabled pass.  Identity (the
        SAME function object) when nothing is enabled — the off path is
        byte-for-byte the pre-pipeline program."""
        live = self.enabled()
        for p in reversed(live):
            apply_fn = p.wrap_apply(apply_fn)
        return apply_fn

    # -- serialization -------------------------------------------------
    def to_json(self) -> list:
        return [{"pass": p.name, "enabled": bool(p.enabled),
                 "config": p.config_json()} for p in self.passes]

    @classmethod
    def from_json(cls, recs) -> "PassPipeline":
        passes = []
        for rec in recs or ():
            pcls = resolve_pass_type(rec["pass"])
            p = pcls.from_config(rec.get("config") or {})
            p.enabled = bool(rec.get("enabled", True))
            passes.append(p)
        return cls(passes)

    def __repr__(self):
        inner = ", ".join(
            p.name + ("" if p.enabled else "(off)") for p in self.passes)
        return f"<PassPipeline [{inner}]>"


def apply_env_toggles(pipeline: PassPipeline,
                      environ=None) -> PassPipeline:
    """MX_PASSES: comma-separated pass toggles applied to a constructed
    pipeline.  ``-name`` force-disables the named pass (a no-op when the
    pipeline doesn't carry it); a bare ``name`` asserts the pass is
    registered (reserved for future force-enable semantics — enabling
    needs pass-specific config, which env strings don't carry).  Any
    token naming an UNREGISTERED pass raises naming the registered set —
    a typoed knob must fail loudly, not silently serve the wrong
    program."""
    import os

    environ = environ if environ is not None else os.environ
    raw = (environ.get("MX_PASSES") or "").strip()
    if not raw:
        return pipeline
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        disable = tok.startswith("-")
        name = tok[1:] if disable else tok
        resolve_pass_type(name)  # unknown -> loud MXNetError
        if disable:
            for p in pipeline.passes:
                if p.name == name:
                    p.enabled = False
    return pipeline
