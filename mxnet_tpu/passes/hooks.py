"""The ONE dispatch consultation point of the pass pipeline.

``ops/registry._invoke_impl`` reads exactly one module global —
``_OP_HOOKS`` — per op call.  When no pass is active the tuple is empty
and dispatch pays a single falsy check, byte-for-byte the pre-pipeline
cost (the contract the PR 15 AMP global established, now owned here for
every pass).  mxlint's ``pass-outside-pipeline`` rule pins this: any
OTHER module-global consultation added to ``_invoke_impl`` is a finding.

Active passes appear as hook objects implementing the two rewrite verbs
the dispatch point offers:

  * ``rewrite_inputs(op_name, inputs) -> inputs`` — edit one op call's
    NDArray inputs before dispatch (the AMP cast pass);
  * ``substitute(op_name, attrs) -> fn | None`` — swap the op's FCompute
    for an alternative implementation inside a trace (the fused-kernel
    pass); only consulted on the traced branch, so eager dispatch never
    pays a registry lookup.

This module is import-spine-safe: stdlib only, no jax/numpy.
"""
from __future__ import annotations

import contextlib

__all__ = ["OpHook", "op_hook", "active"]

_OP_HOOKS = ()   # tuple of active OpHook objects, innermost scope LAST


class OpHook:
    """Protocol/default base for a dispatch hook: both verbs are no-ops
    so a pass overrides only the one it needs."""

    def rewrite_inputs(self, op_name, inputs):
        return inputs

    def substitute(self, op_name, attrs):
        return None


def active() -> bool:
    return bool(_OP_HOOKS)


@contextlib.contextmanager
def op_hook(hook):
    """Push ``hook`` for the ops dispatched inside the block.  Hooks
    nest and restore exactly like the precision scopes they generalize;
    trace-time state, set by one thread around one trace."""
    global _OP_HOOKS
    prev = _OP_HOOKS
    _OP_HOOKS = prev + (hook,)
    try:
        yield
    finally:
        _OP_HOOKS = prev
