"""Graph pass pipeline (docs/PRECISION.md §Pass pipeline).

A Relay-style pass manager (arXiv:1810.00952) over the repo's single op
dispatch point: named, composable, individually-toggleable
:class:`GraphPass` objects in an ordered :class:`PassPipeline` whose ONE
shared ``signature()`` feeds every executable fingerprint (training
hyper_sig, serving engine, the ``plan`` telemetry event).  The dispatch
hook (``hooks._OP_HOOKS``) is the only module global
``ops/registry._invoke_impl`` consults — pinned by mxlint's
``pass-outside-pipeline`` rule.

Env surface (env_vars.py): MX_PASSES (toggles), MX_PALLAS_FUSED
(fused-kernel pass), MX_SERVE_INT4 + MX_QUANT_GROUP (int4 pass, via
precision/quantize.py).
"""
from . import hooks
from .pipeline import (GraphPass, PassPipeline, apply_env_toggles,
                       available_passes, register_pass_type,
                       resolve_pass_type)
from .builtin import (AmpPass, FusedKernelPass, QuantizeInt4Pass,
                      QuantizeInt8Pass, fused_kernels_from_env,
                      pipeline_for_serving, pipeline_for_training)

__all__ = ["GraphPass", "PassPipeline", "register_pass_type",
           "available_passes", "resolve_pass_type", "apply_env_toggles",
           "AmpPass", "QuantizeInt8Pass", "QuantizeInt4Pass",
           "FusedKernelPass", "fused_kernels_from_env",
           "pipeline_for_training", "pipeline_for_serving", "hooks"]
