"""Fault injection + preemption handling (SURVEY §5.3 robustness layer).

The reference has no failure story below epoch granularity: a dead worker
stalls ``dist_sync`` forever and a torn checkpoint write makes the job
unrecoverable.  This module is the *testable* half of the fault-tolerance
layer: an env-driven chaos harness whose hooks are wired into
``AsyncCheckpointer`` (checkpoint.py) so every failure path — worker crash,
crash mid-write, torn write, slow disk — can be reproduced on demand, plus
the SIGTERM preemption handler that turns a pod eviction into one final
synchronous checkpoint and a distinguishable exit code.

Fault spec grammar (``MX_FAULT_SPEC``, ';'-separated specs)::

    spec       := kind (":" key "=" value)*
    kind       := "crash" | "crash-write" | "torn-write" | "slow-write"
                | "oom" | "crash-rendezvous"
    key        := "step" | "ms" | "file" | "rank" | "shard"
                | "if-restart" | "if-world"

  crash:step=N        hard os._exit(EXIT_INJECTED_CRASH) when the training
                      step counter reaches N (before N's checkpoint is
                      enqueued — deterministic: step N is never on disk)
  oom:step=N          raise a synthetic RESOURCE_EXHAUSTED inside step N's
                      dispatch (DataParallelStep calls on_dispatch before
                      handing the program to jax), so the OOM post-mortem
                      path — memwatch.emit_oom_report + the supervisor's
                      death diagnosis — is testable without real HBM
                      exhaustion
  crash-write:step=N  die mid-write of step N's checkpoint: payload files
                      are on disk but meta.json is not, and the staging
                      ``.tmp-N`` dir is left behind (never published)
  torn-write:step=N   publish step N, then truncate its files in place —
                      the on-disk shape of a power loss between write and
                      fsync; file=meta|params|all (default all) picks which.
                      shard=R instead corrupts ONE rank's shard file
                      (params-shard-R.nd) of a shard-granular checkpoint —
                      the single-torn-shard chaos shape (validation must
                      reject the step and restore fall back)
  slow-write:ms=M     sleep M ms at the start of every checkpoint write
                      (step=N restricts it to one write)
  crash-rendezvous    die DURING the gang rendezvous (parallel/dist.py
                      calls on_rendezvous right before
                      jax.distributed.initialize) — the re-rendezvous
                      failure shape of an elastic resize; no step=

Qualifiers on any spec: ``rank=R`` fires only on that worker
(MX_PROC_ID/DMLC_WORKER_ID), ``if-restart=K`` only on gang incarnation
K (MX_RESTART_COUNT, exported by tools/launch.py --max-restarts), and
``if-world=N`` only when the gang's world size (MX_NUM_PROCS/
DMLC_NUM_WORKER) is N — so ``crash:step=30:rank=1:if-restart=0`` kills
rank 1 on the first attempt and lets the restarted gang run clean, and
``crash:step=30:rank=2:if-world=3`` kills rank 2 *permanently at world
size 3* (every incarnation) while letting an elastic resize to 2 ranks
(tools/launch.py --elastic) run clean — the scriptable "lost host"
(docs/FAULT_TOLERANCE.md §Elastic resize).
"""
from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

from .base import MXNetError

__all__ = ["EXIT_INJECTED_CRASH", "EXIT_PREEMPTED", "Fault", "parse_spec",
           "active_faults", "install_preemption_handler"]

# Exit code of an injected `crash` fault — distinguishable from a real bug's
# traceback exit (1) and from signal deaths (negative returncodes).
EXIT_INJECTED_CRASH = 57
# Exit code after a SIGTERM-triggered final checkpoint ("clean preemption").
# tools/launch.py hard-codes the same value (it must not import jax).
EXIT_PREEMPTED = 83

_KINDS = ("crash", "crash-write", "torn-write", "slow-write", "oom",
          "crash-rendezvous")
_KEYS = ("step", "ms", "file", "rank", "shard", "if-restart",
         "if-world")


class Fault:
    """One parsed fault: kind + trigger qualifiers."""

    __slots__ = ("kind", "step", "ms", "file", "rank", "shard",
                 "if_restart", "if_world")

    def __init__(self, kind: str, step: Optional[int] = None,
                 ms: Optional[int] = None, file: str = "all",
                 rank: Optional[int] = None,
                 shard: Optional[int] = None,
                 if_restart: Optional[int] = None,
                 if_world: Optional[int] = None):
        self.kind = kind
        self.step = step
        self.ms = ms
        self.file = file
        self.rank = rank
        self.shard = shard
        self.if_restart = if_restart
        self.if_world = if_world

    def __repr__(self):
        quals = [f"{k}={v}" for k in _KEYS
                 if (v := getattr(self, k.replace("-", "_"), None))
                 is not None and not (k == "file" and v == "all")]
        return f"Fault({self.kind}:{':'.join(quals)})"

    def applies_here(self) -> bool:
        """Rank / incarnation qualifiers against this process's env."""
        if self.rank is not None:
            r = os.environ.get("MX_PROC_ID",
                               os.environ.get("DMLC_WORKER_ID", "0"))
            if int(r) != self.rank:
                return False
        if self.if_restart is not None:
            if int(os.environ.get("MX_RESTART_COUNT", "0")) != self.if_restart:
                return False
        if self.if_world is not None:
            w = os.environ.get("MX_NUM_PROCS",
                               os.environ.get("DMLC_NUM_WORKER", "1"))
            if int(w) != self.if_world:
                return False
        return True


def parse_spec(text: str) -> List[Fault]:
    """Parse an ``MX_FAULT_SPEC`` string; raises MXNetError on bad grammar."""
    faults = []
    for spec in filter(None, (s.strip() for s in text.split(";"))):
        parts = spec.split(":")
        kind = parts[0].strip()
        if kind not in _KINDS:
            raise MXNetError(
                f"MX_FAULT_SPEC: unknown fault kind {kind!r} in {spec!r} "
                f"(known: {', '.join(_KINDS)})")
        kw = {}
        for qual in parts[1:]:
            key, sep, val = qual.partition("=")
            key = key.strip()
            if not sep or key not in _KEYS:
                raise MXNetError(
                    f"MX_FAULT_SPEC: bad qualifier {qual!r} in {spec!r} "
                    f"(known: {', '.join(_KEYS)})")
            if key == "file":
                if val not in ("meta", "params", "all"):
                    raise MXNetError(
                        f"MX_FAULT_SPEC: file= must be meta|params|all, "
                        f"got {val!r}")
                kw["file"] = val
            else:
                try:
                    kw[key.replace("-", "_")] = int(val)
                except ValueError:
                    raise MXNetError(
                        f"MX_FAULT_SPEC: {key}= wants an integer, got "
                        f"{val!r}") from None
        f = Fault(kind, **kw)
        if f.kind in ("crash", "crash-write", "torn-write", "oom") \
                and f.step is None:
            raise MXNetError(f"MX_FAULT_SPEC: {f.kind} requires step=N")
        if f.kind == "slow-write" and f.ms is None:
            raise MXNetError("MX_FAULT_SPEC: slow-write requires ms=N")
        if f.shard is not None and f.kind != "torn-write":
            raise MXNetError(
                "MX_FAULT_SPEC: shard=R only applies to torn-write "
                "(it selects which rank's shard file to corrupt in a "
                "shard-granular checkpoint)")
        if f.kind == "crash-rendezvous" and f.step is not None:
            raise MXNetError(
                "MX_FAULT_SPEC: crash-rendezvous fires at rendezvous time, "
                "before any training step exists — step= does not apply "
                "(scope it with rank=/if-restart=/if-world=)")
        faults.append(f)
    return faults


# Parsed-spec cache keyed on the raw env value so the per-step hook is a
# dict lookup + string compare, not a re-parse.
_cached_text: Optional[str] = None
_cached_faults: List[Fault] = []


def active_faults() -> List[Fault]:
    text = os.environ.get("MX_FAULT_SPEC", "")
    global _cached_text, _cached_faults
    if text != _cached_text:
        _cached_faults = parse_spec(text)
        _cached_text = text
    return _cached_faults


def _match(kind: str, step: Optional[int] = None):
    for f in active_faults():
        if f.kind != kind or not f.applies_here():
            continue
        if step is not None and f.step is not None and f.step != step:
            continue
        return f
    return None


# ---------------------------------------------------------------------------
# hooks (called by AsyncCheckpointer; no-ops when MX_FAULT_SPEC is unset)
# ---------------------------------------------------------------------------
def on_train_step(step: int) -> None:
    """`crash` injection point — AsyncCheckpointer.step() calls this right
    after incrementing its counter, before any checkpoint is enqueued."""
    f = _match("crash", step)
    if f is not None and f.step == step:
        print(f"mxnet_tpu.fault: injected crash at step {step}", flush=True)
        os._exit(EXIT_INJECTED_CRASH)


def on_dispatch(step: int) -> None:
    """``oom`` injection point — ``DataParallelStep._step_impl`` calls
    this right before handing the step program to jax.  The synthetic
    error spells RESOURCE_EXHAUSTED exactly like PjRt's XlaRuntimeError
    status text, so the same ``memwatch.is_resource_exhausted`` match
    routes it through the real OOM post-mortem path."""
    f = _match("oom", step)
    if f is not None and f.step == step:
        raise MXNetError(
            f"RESOURCE_EXHAUSTED: injected device OOM at step {step} "
            f"(MX_FAULT_SPEC): out of memory while allocating step "
            f"buffers")


def on_rendezvous() -> None:
    """``crash-rendezvous`` injection point — ``parallel.dist`` calls this
    right before ``jax.distributed.initialize``, so an elastic
    re-rendezvous (tools/launch.py --elastic) can be made to fail on a
    chosen rank/incarnation/world size.  Scoped with ``if-world=N`` it
    models a host that comes back broken: admitted into the resized gang
    but dead before the coordination service ever sees it."""
    f = _match("crash-rendezvous")
    if f is not None:
        print("mxnet_tpu.fault: injected crash during rendezvous",
              flush=True)
        os._exit(EXIT_INJECTED_CRASH)


def on_write_begin(step: int) -> None:
    f = _match("slow-write", step)
    if f is not None:
        time.sleep(f.ms / 1000.0)


def on_write_mid(step: int) -> None:
    """Called between the payload writes and meta.json — a crash here
    leaves a half-filled ``.tmp-<step>`` staging dir, never published."""
    f = _match("crash-write", step)
    if f is not None and f.step == step:
        print(f"mxnet_tpu.fault: injected crash mid-write of step {step}",
              flush=True)
        os._exit(EXIT_INJECTED_CRASH)


def on_write_published(step: int, final_dir: str) -> None:
    """Called after step's checkpoint dir is published and ``latest``
    updated; torn-write truncates files in place so the *newest* checkpoint
    is the corrupt one (the fallback path load must survive)."""
    f = _match("torn-write", step)
    if f is None or f.step != step:
        return
    if f.shard is not None:
        # shard-granular checkpoints: corrupt exactly rank R's shard
        # file — the single-shard-corruption chaos shape (the whole step
        # must fail validation and restore fall back past it)
        targets = [f"params-shard-{f.shard}.nd"]
    else:
        targets = {"meta": ["meta.json"], "params": ["params.nd"],
                   "all": ["meta.json", "params.nd"]}[f.file]
    for fname in targets:
        path = os.path.join(final_dir, fname)
        if not os.path.exists(path):
            continue
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(size // 2)
    print(f"mxnet_tpu.fault: tore checkpoint step {step} "
          f"({'+'.join(targets)})", flush=True)


# ---------------------------------------------------------------------------
# preemption handling
# ---------------------------------------------------------------------------
def install_preemption_handler(ckpt, params, trainer=None,
                               exit_code: int = EXIT_PREEMPTED):
    """Turn SIGTERM (pod preemption, or the gang supervisor's fan-out) into
    one final *synchronous* checkpoint and a clean, distinguishable exit.

    Best-effort by design: python delivers signals between bytecodes, so a
    rank blocked inside a native collective (waiting on a dead peer) may
    never run the handler — the supervisor's bounded SIGKILL escalation
    reaps it, and the gang resumes from that rank's last *published*
    checkpoint instead.  Because the final checkpoint lands at whatever
    step SIGTERM caught this rank, a restarted sync-SGD gang must agree on
    a common resume step — see ``checkpoint.agree_resume_step``.

    Caveat for exact-trajectory resume: SIGTERM can land mid-update (the
    Trainer's per-param python loop) or after an update whose step() call
    hasn't run yet, so the off-cycle snapshot may mix in (part of) the
    NEXT step's update under the previous step's label.  Gang resume is
    immune (it agrees on scheduled steps only); a solo run that needs
    bit-exact resumption should restore its last *scheduled* step —
    ``restore(dir, net, trainer,
    step=latest_valid_step(dir, multiple_of=save_every))`` — and treat the
    off-cycle checkpoint as a freshest-effort snapshot.

    Returns the installed handler (mainly for tests)."""
    # bound OUTSIDE the handler: even `import sys` re-enters the import
    # machinery (and its lock) when run inside a signal handler — the
    # exact deadlock the sys.modules lookup below exists to avoid
    # (mxlint signal-unsafe)
    import sys as _sys

    def _handler(signum, frame):
        # drain the async dispatch windows first: a pending step must land
        # in the device buffers before the sync snapshot reads them, and a
        # deferred failure must not masquerade as a checkpoint error.
        # sys.modules lookup (not import): if the async layer was never
        # imported, nothing can be pending — and a signal handler must not
        # run fresh imports.
        _async = _sys.modules.get("mxnet_tpu.parallel.async_loss")
        if _async is not None:
            try:
                _async.drain_all()
            except BaseException:  # noqa: BLE001 — dying anyway
                pass
        step = None
        try:
            step = ckpt.save_now(params, trainer=trainer)
        except BaseException as e:  # noqa: BLE001 — dying anyway, by design
            print(f"mxnet_tpu.fault: preemption checkpoint failed: {e}",
                  flush=True)
        if step:
            print(f"mxnet_tpu.fault: preempted; final checkpoint at step "
                  f"{step}", flush=True)
        os._exit(exit_code)

    signal.signal(signal.SIGTERM, _handler)
    return _handler
