"""Runtime feature detection (reference: python/mxnet/runtime.py Features()
~L40 over src/libinfo.cc compile-time flags).

Features reflect what this build actually provides: TPU/XLA capabilities
replace the CUDA/MIOpen/MKLDNN flag set.
"""
from __future__ import annotations

from collections import namedtuple

__all__ = ["Feature", "Features", "feature_list"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    feats = {
        "TPU": False,
        "XLA": True,
        "PALLAS": True,
        "CUDA": False,
        "CUDNN": False,
        "MIOPEN": False,
        "NCCL": False,
        "ICI_COLLECTIVES": True,
        "DIST_KVSTORE": True,
        "OPENCV": False,
        "BLAS_OPEN": True,
        "F16C": True,
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": True,
    }
    try:
        import jax

        feats["TPU"] = any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        # feature probe: jax missing, backend init failure, or a dead
        # TPU runtime all mean the same thing here — no TPU visible
        pass
    try:
        import cv2  # noqa: F401

        feats["OPENCV"] = True
    except ImportError:
        pass
    return feats


class Features(dict):
    def __init__(self):
        super().__init__(
            (name, Feature(name, enabled))
            for name, enabled in _detect().items())

    def __repr__(self):
        return f"[{', '.join(f'{v.name}' + (' ✔' if v.enabled else ' ✖') for v in self.values())}]"

    def is_enabled(self, feature_name: str) -> bool:
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
