"""ImageRecordIter: the high-throughput RecordIO image pipeline.

Reference parity: src/io/iter_image_recordio_2.cc (ImageRecordIOParser2:
chunked read -> per-thread JPEG decode -> augment -> batch assembly, with
PrefetcherIter double buffering ~L400).

Implementation: a thread pool decodes/augments (OpenCV releases the GIL, so
threads scale like the reference's OMP workers) feeding a bounded prefetch
queue of ready batches; batches land as NDArrays ready for async H2D.  A
C-extension decode core (src/) can be swapped in transparently; this module
is the contract.
"""
from __future__ import annotations

import os
import queue
import random as pyrandom
import threading
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter"]


class ImageRecordIter(DataIter):
    """shuffle_chunk_size (MB) bounds shuffle memory in the NATIVE pipeline
    (chunk-local reads, reference semantics); the pure-Python fallback
    reads by index and always full-shuffles — a strictly better mix, so
    the parameter is a no-op there."""

    def __init__(self, path_imgrec=None, path_imgidx=None, data_shape=None,
                 batch_size=1, label_width=1, shuffle=False,
                 shuffle_chunk_size=0, preprocess_threads=4, prefetch_buffer=4,
                 rand_crop=False, rand_mirror=False, resize=0,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, scale=1.0, seed=0, round_batch=True,
                 brightness=0.0, contrast=0.0, saturation=0.0,
                 random_h=0, random_s=0, random_l=0, pca_noise=0.0,
                 shuffle_chunk_seed=0, ctx=None, dtype="float32", **kwargs):
        super().__init__(batch_size)
        if path_imgrec is None or data_shape is None:
            raise MXNetError("path_imgrec and data_shape are required")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._threads = max(1, preprocess_threads)
        self._prefetch = max(1, prefetch_buffer)
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self._std = np.array([std_r, std_g, std_b], np.float32)
        self._scale = scale
        # color jitter for the pure-Python fallback path (the native path
        # applies the same jitters in C++) — constructor args must mean
        # the same thing whichever pipeline loaded
        from ..image import (ColorJitterAug, HueJitterAug, LightingAug)

        self._color_augs = []
        b = brightness + random_l / 255.0
        s = saturation + random_s / 255.0
        if b or contrast or s:
            self._color_augs.append(ColorJitterAug(b, contrast, s))
        if random_h:
            self._color_augs.append(HueJitterAug(random_h / 180.0))
        if pca_noise > 0:
            self._color_augs.append(LightingAug(
                pca_noise, eigval=np.array([55.46, 4.794, 1.148]),
                eigvec=np.array([[-0.5675, 0.7192, 0.4009],
                                 [-0.5808, -0.0045, -0.8140],
                                 [-0.5836, -0.6948, 0.4203]])))
        self._dtype = dtype
        self._round_batch = round_batch
        self._rng = pyrandom.Random(seed)
        self._lock = threading.Lock()
        self.provide_data = [DataDesc("data", (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc("softmax_label",
                                       (batch_size, label_width))]
        self._queue: Optional[queue.Queue] = None
        self._workers: List[threading.Thread] = []

        # prefer the native C++ pipeline (src/mxio.cc) when built —
        # reference parity with iter_image_recordio_2.cc's threaded parser
        self._native = None
        from . import native as _native_mod

        if _native_mod.available() and dtype == "float32":
            try:
                # HSL jitter mapping (reference image_aug_default.cc):
                # random_h is in degrees (OpenCV hue unit = 2 deg);
                # random_s / random_l are on the 0-255 scale -> fractions
                self._native = _native_mod.NativeImageIter(
                    path_imgrec, batch_size, self.data_shape,
                    preprocess_threads=self._threads, shuffle=shuffle,
                    seed=seed ^ shuffle_chunk_seed, resize=resize,
                    rand_crop=rand_crop,
                    rand_mirror=rand_mirror, scale=scale,
                    mean=self._mean, std=self._std,
                    label_width=label_width, prefetch=self._prefetch,
                    brightness=brightness + random_l / 255.0,
                    contrast=contrast,
                    saturation=saturation + random_s / 255.0,
                    hue=random_h / 2.0, pca_noise=pca_noise,
                    shuffle_chunk_mb=float(shuffle_chunk_size))
                self._native_batches = (
                    self._native.num_records // batch_size
                    if round_batch else
                    (self._native.num_records + batch_size - 1) // batch_size)
                self._consumed = 0
                return
            except RuntimeError:
                self._native = None

        # pure Python fallback needs the indexed record file
        from .. import recordio

        if path_imgidx is None:
            path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
        self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
        if not self._rec.keys:
            raise MXNetError(f"{path_imgidx}: empty or missing index")
        self._start_epoch()

    # ------------------------------------------------------------------
    def _start_epoch(self):
        self._stop_workers()
        order = list(self._rec.keys)
        if self._shuffle:
            self._rng.shuffle(order)
        nbatch = len(order) // self.batch_size if self._round_batch else \
            (len(order) + self.batch_size - 1) // self.batch_size
        self._batches = [
            order[i * self.batch_size: (i + 1) * self.batch_size]
            for i in range(nbatch)
        ]
        self._queue = queue.Queue(maxsize=self._prefetch)
        # _stop_workers() joined the old epoch's workers above, but the
        # cursor is the one field the NEW workers also mutate — taking the
        # assignment lock here makes the reset manifestly ordered instead
        # of relying on the join for the happens-before
        with self._lock:
            self._batch_cursor = 0
        self._produced = 0
        self._consumed = 0
        self._stop = False
        self._workers = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(self._threads)
        ]
        for w in self._workers:
            w.start()

    def _stop_workers(self):
        self._stop = True
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        for w in self._workers:
            w.join(timeout=1.0)
        self._workers = []

    def _next_assignment(self):
        with self._lock:
            if self._batch_cursor >= len(self._batches):
                return None, None
            i = self._batch_cursor
            self._batch_cursor += 1
            return i, self._batches[i]

    def _worker(self):
        from .. import image as img_mod
        from .. import recordio

        c, h, w = self.data_shape
        while not self._stop:
            i, keys = self._next_assignment()
            if keys is None:
                return
            data = np.zeros((self.batch_size, c, h, w), np.float32)
            labels = np.zeros((self.batch_size, self.label_width), np.float32)
            for slot, key in enumerate(keys):
                with self._lock:
                    raw = self._rec.read_idx(key)
                header, buf = recordio.unpack(raw)
                img = img_mod.imdecode(buf, to_ndarray=False)
                if self._resize:
                    img = img_mod.resize_short(img, self._resize)
                if img.shape[0] != h or img.shape[1] != w:
                    if self._rand_crop and img.shape[0] >= h and img.shape[1] >= w:
                        img = img_mod.random_crop(img, (w, h))[0]
                    else:
                        img = img_mod.center_crop(img, (w, h))[0]
                    if img.shape[:2] != (h, w):
                        img = img_mod.imresize(img, w, h)
                if self._rand_mirror and self._rng.random() < 0.5:
                    img = img[:, ::-1]
                for aug in self._color_augs:
                    img = aug(img)
                arr = np.asarray(img, np.float32)
                arr = (arr - self._mean) / self._std * self._scale
                data[slot] = arr.transpose(2, 0, 1)
                lab = np.atleast_1d(np.asarray(header.label, np.float32))
                labels[slot, : len(lab)] = lab[: self.label_width]
            self._queue.put((i, data, labels))

    # ------------------------------------------------------------------
    def reset(self):
        if self._native is not None:
            self._native.reset()
            self._consumed = 0
            return
        self._start_epoch()

    def iter_next(self):
        if self._native is not None:
            return self._consumed < self._native_batches
        return self._consumed < len(self._batches)

    def next(self):
        from .. import ndarray as nd

        if self._native is not None:
            if self._consumed >= self._native_batches:
                raise StopIteration
            out = self._native.next_batch()
            if out is None:
                raise StopIteration
            data, labels = out
            self._consumed += 1
            return DataBatch(
                data=[nd.array(data, dtype=self._dtype)],
                label=[nd.array(labels)],
                pad=0, provide_data=self.provide_data,
                provide_label=self.provide_label)
        if self._consumed >= len(self._batches):
            raise StopIteration
        _, data, labels = self._queue.get()
        self._consumed += 1
        return DataBatch(
            data=[nd.array(data, dtype=self._dtype)],
            label=[nd.array(labels)],
            pad=0, provide_data=self.provide_data,
            provide_label=self.provide_label)
