"""ctypes binding for libmxio.so — the native RecordIO image pipeline.

Reference parity: the C ABI role of src/c_api for the IO subsystem
(MXDataIterCreateIter -> iter_image_recordio_2.cc); here a narrow dedicated
boundary (SURVEY.md §7.1: "keep a narrow libmx_io C++ boundary").

The library is built by `make -C src` (no pybind11 in this image — plain
ctypes over an extern-C ABI).  `available()` gates every use so the pure
Python pipeline remains the fallback.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "lib",
                         "libmxio.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("MXNET_USE_NATIVE_IO", "1") == "0":
        return None
    try:
        lib = ctypes.CDLL(os.path.abspath(_LIB_PATH))
    except OSError:
        return None
    lib.MXIOImageIterCreate.restype = ctypes.c_void_p
    lib.MXIOImageIterCreate.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_float,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, ctypes.c_int, ctypes.c_float, ctypes.c_float,
        ctypes.c_float]
    lib.MXIOImageIterCreate2.restype = ctypes.c_void_p
    lib.MXIOImageIterCreate2.argtypes = (
        lib.MXIOImageIterCreate.argtypes
        + [ctypes.c_float, ctypes.c_float, ctypes.c_float])
    lib.MXIOImageIterNext.restype = ctypes.c_int
    lib.MXIOImageIterNext.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_float),
                                      ctypes.POINTER(ctypes.c_float)]
    lib.MXIOImageIterReset.argtypes = [ctypes.c_void_p]
    lib.MXIOImageIterNumRecords.restype = ctypes.c_longlong
    lib.MXIOImageIterNumRecords.argtypes = [ctypes.c_void_p]
    lib.MXIOImageIterDestroy.argtypes = [ctypes.c_void_p]
    lib.MXIOEncodeJpeg.restype = ctypes.c_int
    lib.MXIOEncodeJpeg.argtypes = [
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int]
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None


class NativeImageIter:
    """Thin wrapper owning one native iterator handle."""

    def __init__(self, path_imgrec: str, batch_size: int, data_shape,
                 preprocess_threads=4, shuffle=False, seed=0, resize=0,
                 rand_crop=False, rand_mirror=False, scale=1.0,
                 mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0), label_width=1,
                 prefetch=2, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0, pca_noise=0.0, shuffle_chunk_mb=0.0):
        lib = _load()
        if lib is None:
            raise RuntimeError("libmxio.so not available (make -C src)")
        c, h, w = data_shape
        mean_arr = (ctypes.c_float * 3)(*[float(m) for m in mean])
        std_arr = (ctypes.c_float * 3)(*[float(s) for s in std])
        self._lib = lib
        self._handle = lib.MXIOImageIterCreate2(
            path_imgrec.encode(), batch_size, c, h, w,
            int(preprocess_threads), int(bool(shuffle)), int(seed),
            int(resize), int(bool(rand_crop)), int(bool(rand_mirror)),
            float(scale), mean_arr, std_arr, int(label_width), int(prefetch),
            float(brightness), float(contrast), float(saturation),
            float(hue), float(pca_noise), float(shuffle_chunk_mb))
        if not self._handle:
            raise RuntimeError(f"native iter failed to open {path_imgrec}")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width

    @property
    def num_records(self) -> int:
        return int(self._lib.MXIOImageIterNumRecords(self._handle))

    def next_batch(self):
        """Returns (data NCHW float32, labels) or None at epoch end."""
        data = np.empty((self.batch_size,) + self.data_shape, np.float32)
        labels = np.empty((self.batch_size, self.label_width), np.float32)
        ok = self._lib.MXIOImageIterNext(
            self._handle,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if not ok:
            return None
        return data, labels

    def reset(self):
        self._lib.MXIOImageIterReset(self._handle)

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.MXIOImageIterDestroy(handle)
            self._handle = None


def encode_jpeg(rgb: np.ndarray, quality: int = 95) -> bytes:
    """JPEG-encode an RGB uint8 HWC array via the native lib."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libmxio.so not available")
    rgb = np.ascontiguousarray(rgb, np.uint8)
    h, w = rgb.shape[:2]
    cap = h * w * 3 + 1024
    out = (ctypes.c_ubyte * cap)()
    n = lib.MXIOEncodeJpeg(
        rgb.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), h, w,
        int(quality), out, cap)
    if n < 0:
        raise RuntimeError("jpeg encode failed")
    return bytes(out[:n])
