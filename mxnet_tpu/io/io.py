"""Core data-iterator API (reference: python/mxnet/io/io.py)."""
from __future__ import annotations

from collections import namedtuple
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "CSVIter", "LibSVMIter", "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return (f"DataDesc[{self.name},{self.shape},{self.dtype},"
                f"{self.layout}]")

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("data must be a list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise MXNetError("label must be a list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return (f"{type(self).__name__}: data shapes: {data_shapes} "
                f"label shapes: {label_shapes}")


class DataIter:
    """Base iterator (reference ~L200)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    from ..ndarray import NDArray

    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError(
            f"Input must be NDArray, numpy.ndarray, list or dict; got "
            f"{type(data)}")
    return list(data.items())


class NDArrayIter(DataIter):
    """Iterator over in-memory arrays (reference ~L600)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self._size())
        if shuffle:
            np.random.shuffle(self.idx)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        num = self._size()
        if last_batch_handle == "discard":
            self.num_data = (num // batch_size) * batch_size
        else:
            self.num_data = num

    def _size(self):
        k, v = self.data[0]
        return len(v)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _take(self, arrays):
        from .. import ndarray as nd
        from ..ndarray import NDArray

        out = []
        for _, v in arrays:
            vnp = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
            end = self.cursor + self.batch_size
            sel = self.idx[self.cursor: end]
            part = vnp[sel]
            if len(part) < self.batch_size:  # pad by wrapping
                if self.last_batch_handle == "pad":
                    extra = vnp[self.idx[: self.batch_size - len(part)]]
                    part = np.concatenate([part, extra])
                elif self.last_batch_handle == "roll_over":
                    pass
            out.append(nd.array(part, dtype=part.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference ~L300)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """libsvm-format iterator yielding csr batches (reference:
    src/io/iter_libsvm.cc).  Rows are kept as (indices, values) pairs —
    only one batch is ever densified (batch_size x n_feat), so huge
    feature spaces don't blow up host memory.

    Indexing: pass one_based=True for 1-based files (liblinear/svmlight
    convention) or one_based=False for 0-based.  The default (None) keeps
    the legacy heuristic — shift when the max index equals n_feat (it would
    be out of range 0-based) — but warns when it triggers, because a
    1-based file that never uses the last feature id is indistinguishable
    from a 0-based one (r3 advisor finding).
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, round_batch=True, one_based=None, **kwargs):
        super().__init__(batch_size)
        self._n_feat = int(np.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                pairs = [p.split(":") for p in parts[1:]]
                rows.append((np.array([int(k) for k, _ in pairs], np.int64),
                             np.array([float(v) for _, v in pairs],
                                      np.float32)))
        if label_libsvm is not None:
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        labels.append(float(line.split()[0]))
        max_idx = max((int(i.max()) for i, _ in rows if i.size), default=0)
        min_idx = min((int(i.min()) for i, _ in rows if i.size), default=0)
        has_feats = any(i.size for i, _ in rows)
        if one_based is True:
            if has_feats and min_idx < 1:
                raise MXNetError(
                    f"one_based=True but found feature index {min_idx}")
            rows = [(i - 1, v) for i, v in rows]
        elif one_based is None and max_idx >= self._n_feat \
                and min_idx >= 1 and max_idx == self._n_feat:
            import warnings

            warnings.warn(
                "LibSVMIter: max feature index equals n_feat; assuming a "
                "1-based file and shifting indices.  Pass one_based=True/"
                "False to silence this heuristic.", stacklevel=2)
            rows = [(i - 1, v) for i, v in rows]
        max_idx = max((int(i.max()) for i, _ in rows if i.size), default=0)
        if max_idx >= self._n_feat:
            raise MXNetError(
                f"libsvm feature index {max_idx} out of range for "
                f"data_shape {data_shape}")
        self._rows = rows
        self._labels = np.asarray(labels, np.float32)
        self._round = round_batch
        self._pos = 0

    @property
    def provide_data(self):
        return [DataDesc(name="data",
                         shape=(self.batch_size, self._n_feat))]

    @property
    def provide_label(self):
        return [DataDesc(name="softmax_label", shape=(self.batch_size,))]

    def reset(self):
        self._pos = 0

    def next(self):
        from ..ndarray import array as nd_array

        n = len(self._rows)
        if self._pos >= n:
            raise StopIteration
        idxs = list(range(self._pos, min(self._pos + self.batch_size, n)))
        pad = self.batch_size - len(idxs)
        if pad:
            if not self._round:
                raise StopIteration
            idxs += list(range(pad))  # wrap-around, reference round_batch
        self._pos += self.batch_size
        dense = np.zeros((self.batch_size, self._n_feat), np.float32)
        for r, j in enumerate(idxs):
            ci, cv = self._rows[j]
            dense[r, ci] = cv
        label = self._labels[idxs]
        csr = nd_array(dense).tostype("csr")
        return DataBatch([csr], [nd_array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False


class PrefetchingIter(DataIter):
    """Background-thread prefetch wrapper (reference: io.py
    PrefetchingIter over threadediter) — overlaps host-side batch prep
    with device compute, the python analog of the C++ PrefetcherIter.

    rename_data/rename_label: list with one dict mapping original
    descriptor names to new names (reference semantics for binding under
    different arg names).
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here wraps exactly one iter; "
                             "compose multiple with a zip-style wrapper")
        self._iter = iters[0]
        super().__init__(getattr(self._iter, "batch_size", 0))
        self._rename_data = (rename_data[0] if rename_data else None)
        self._rename_label = (rename_label[0] if rename_label else None)
        import queue

        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = False
        self._done = False
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def worker():
            while not self._stop:
                try:
                    batch = self._iter.next()
                except StopIteration:
                    self._queue.put(("done", None))
                    return
                except Exception as exc:  # propagate to the consumer
                    self._queue.put(("error", exc))
                    return
                self._queue.put(("batch", batch))

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        # drain: let the worker finish, clear the queue, restart
        self._stop = True
        while self._thread.is_alive():
            try:
                self._queue.get(timeout=0.1)
            except Exception:
                pass
        self._thread.join()
        self._iter.reset()
        self._stop = False
        self._done = False
        import queue

        self._queue = queue.Queue(maxsize=2)
        self._start()

    def next(self):
        if self._done:
            raise StopIteration  # repeatable after exhaustion
        kind, payload = self._queue.get()
        if kind == "done":
            self._done = True
            raise StopIteration
        if kind == "error":
            self._done = True
            raise payload
        return payload

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False

    def _renamed(self, descs, mapping):
        if not mapping:
            return descs
        return [DataDesc(name=mapping.get(d.name, d.name), shape=d.shape)
                for d in descs]

    @property
    def provide_data(self):
        return self._renamed(self._iter.provide_data, self._rename_data)

    @property
    def provide_label(self):
        return self._renamed(self._iter.provide_label, self._rename_label)
