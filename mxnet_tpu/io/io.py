"""Core data-iterator API (reference: python/mxnet/io/io.py)."""
from __future__ import annotations

from collections import namedtuple
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "CSVIter", "LibSVMIter", "PrefetchingIter", "DevicePrefetchIter",
           "stage_batches"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return (f"DataDesc[{self.name},{self.shape},{self.dtype},"
                f"{self.layout}]")

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("data must be a list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise MXNetError("label must be a list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return (f"{type(self).__name__}: data shapes: {data_shapes} "
                f"label shapes: {label_shapes}")


class DataIter:
    """Base iterator (reference ~L200)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    from ..ndarray import NDArray

    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError(
            f"Input must be NDArray, numpy.ndarray, list or dict; got "
            f"{type(data)}")
    return list(data.items())


class NDArrayIter(DataIter):
    """Iterator over in-memory arrays (reference ~L600).

    TPU-native extensions over the reference iterator
    (docs/FAULT_TOLERANCE.md §Elastic resize):

    * ``seed`` — a per-iterator RNG.  The reference shuffled through the
      *global* ``np.random`` state, so two interleaved iterators
      perturbed each other and a restarted run could never reproduce an
      epoch's order.  Here each epoch's permutation is derived from
      ``(seed, epoch)`` alone, so the order is reproducible across
      process restarts (the prerequisite for the checkpointable cursor).
      ``seed=None`` draws one from the global stream at construction
      (legacy ``np.random.seed`` determinism preserved) and records it in
      :meth:`get_state` — even an unseeded iterator restores exactly.
    * ``num_parts`` / ``part_index`` — gang sharding over ONE global
      sample order (the ``ImageRecordIter`` contract): every rank holds
      the full arrays, each global batch is ``batch_size * num_parts``
      consecutive samples of the epoch permutation, and rank ``p`` takes
      its ``batch_size`` slice.  The cursor counts GLOBAL samples, so it
      is world-size independent: after an elastic resize the restored
      iterator continues at the same sample position under the new
      ``(num_parts, batch_size)`` — no sample skipped or consumed twice
      even though the per-rank shard boundaries moved.
    * :meth:`get_state` / :meth:`set_state` — the checkpointable position
      (epoch, seed, global sample cursor), saved alongside the model via
      ``AsyncCheckpointer.step(..., extra=...)``.
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None,
                 num_parts=1, part_index=0):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        if num_parts < 1 or not 0 <= part_index < num_parts:
            raise MXNetError(
                f"need 0 <= part_index < num_parts, got part_index="
                f"{part_index} num_parts={num_parts}")
        if num_parts > 1 and last_batch_handle == "roll_over":
            # a short final global batch would hand higher-index parts an
            # empty/shorter slice than their peers — divergent shapes into
            # a sync-SGD collective step; gang sharding supports pad (wrap)
            # and discard, whose per-part shapes stay uniform
            raise MXNetError(
                "num_parts > 1 does not support last_batch_handle="
                "'roll_over' (ragged per-rank final batches); use 'pad' "
                "or 'discard'")
        self.num_parts = int(num_parts)
        self.part_index = int(part_index)
        self._stride = batch_size * self.num_parts
        self.shuffle = shuffle
        if seed is None:
            if shuffle and self.num_parts > 1:
                # each rank drawing its own seed would shard DIFFERENT
                # permutations — samples consumed twice/never with no
                # error; the gang contract requires one agreed seed
                raise MXNetError(
                    "num_parts > 1 with shuffle requires an explicit "
                    "seed: every rank must shard ONE global sample order")
            # drawn from the global stream so legacy global-seed setups
            # stay deterministic; recorded in get_state so restores
            # reproduce the order either way
            seed = int(np.random.randint(0, 2**31 - 1)) if shuffle else 0
        self._seed = int(seed)
        self._epoch = 0
        self.last_batch_handle = last_batch_handle
        self.idx = self._perm()
        self.cursor = -self._stride
        num = self._size()
        if last_batch_handle == "discard":
            self.num_data = (num // self._stride) * self._stride
        else:
            self.num_data = num

    def _size(self):
        k, v = self.data[0]
        return len(v)

    def _perm(self):
        """This epoch's sample order — a pure function of (seed, epoch),
        never of global RNG state or of how many batches were drawn."""
        n = self._size()
        if not self.shuffle:
            return np.arange(n)
        return np.random.default_rng((self._seed, self._epoch)).permutation(n)

    # -- checkpointable position (docs/FAULT_TOLERANCE.md §Elastic resize) --
    def get_state(self) -> dict:
        """JSON-serializable iterator position: (epoch, seed, global
        sample cursor).  The cursor counts samples consumed by ALL parts
        jointly, so the state restores onto a different
        ``(num_parts, batch_size)`` split — the elastic-resize contract."""
        return {"epoch": int(self._epoch), "seed": int(self._seed),
                "sample_cursor": int(max(0, self.cursor + self._stride)),
                "shuffle": bool(self.shuffle),
                "num_data": int(self._size())}

    def set_state(self, state: dict) -> None:
        """Resume exactly where :meth:`get_state` left off — the next
        batch starts at the saved global sample position under THIS
        iterator's stride, on the same (seed, epoch) permutation."""
        if int(state.get("num_data", self._size())) != self._size():
            raise MXNetError(
                f"iterator state was saved over {state.get('num_data')} "
                f"samples but this iterator holds {self._size()} — "
                "restore requires the same dataset")
        self._seed = int(state["seed"])
        self.shuffle = bool(state.get("shuffle", self.shuffle))
        self._epoch = int(state["epoch"])
        self.idx = self._perm()
        self.cursor = int(state["sample_cursor"]) - self._stride

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.label]

    def reset(self):
        self._epoch += 1  # a fresh (seed, epoch) permutation each epoch
        self.idx = self._perm()
        self.cursor = -self._stride

    def iter_next(self):
        self.cursor += self._stride
        if self.last_batch_handle == "discard":
            # the FULL global window must fit: a restored cursor may not
            # be aligned to THIS stride (set_state after a resize), and a
            # straddling window would hand ranks ragged/empty batches —
            # discard means fixed shapes, so the short tail is dropped
            return self.cursor + self._stride <= self._size()
        return self.cursor < self.num_data

    def _sel(self):
        """This part's sample ids for the current global batch: the
        ``batch_size`` slice at ``part_index`` inside the
        ``batch_size * num_parts`` global window at ``cursor``.  In pad
        mode a window reaching past the epoch wraps circularly over the
        permutation (the reference's wrap-from-the-head, generalized to
        parts)."""
        offset = self.cursor + self.part_index * self.batch_size
        end = offset + self.batch_size
        # discard windows are guaranteed by iter_next to fit the RAW
        # size (a restored cursor may be unaligned, so a full window can
        # legitimately reach past the stride-aligned num_data)
        limit = self._size() if self.last_batch_handle == "discard" \
            else self.num_data
        if end <= limit:
            return self.idx[offset:end]
        if self.last_batch_handle == "pad":
            return self.idx[np.arange(offset, end) % self.num_data]
        return self.idx[offset:limit]  # roll_over: short part

    def _take(self, arrays):
        from .. import ndarray as nd
        from ..ndarray import NDArray

        sel = self._sel()
        out = []
        for _, v in arrays:
            vnp = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
            part = vnp[sel]
            out.append(nd.array(part, dtype=part.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getindex(self):
        """Sample ids of this part's current batch (the census surface:
        summing getindex over ranks and steps must cover an epoch exactly
        once — asserted across an elastic resize in tests/test_elastic.py)."""
        if self.cursor < 0:
            return None
        return self._sel().copy()

    def getpad(self):
        if self.last_batch_handle != "pad":
            return 0
        offset = self.cursor + self.part_index * self.batch_size
        pad = offset + self.batch_size - self.num_data
        return max(0, min(self.batch_size, pad))


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference ~L300)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """libsvm-format iterator yielding csr batches (reference:
    src/io/iter_libsvm.cc).  Rows are kept as (indices, values) pairs —
    only one batch is ever densified (batch_size x n_feat), so huge
    feature spaces don't blow up host memory.

    Indexing: pass one_based=True for 1-based files (liblinear/svmlight
    convention) or one_based=False for 0-based.  The default (None) keeps
    the legacy heuristic — shift when the max index equals n_feat (it would
    be out of range 0-based) — but warns when it triggers, because a
    1-based file that never uses the last feature id is indistinguishable
    from a 0-based one (r3 advisor finding).
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, round_batch=True, one_based=None, **kwargs):
        super().__init__(batch_size)
        self._n_feat = int(np.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                pairs = [p.split(":") for p in parts[1:]]
                rows.append((np.array([int(k) for k, _ in pairs], np.int64),
                             np.array([float(v) for _, v in pairs],
                                      np.float32)))
        if label_libsvm is not None:
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        labels.append(float(line.split()[0]))
        max_idx = max((int(i.max()) for i, _ in rows if i.size), default=0)
        min_idx = min((int(i.min()) for i, _ in rows if i.size), default=0)
        has_feats = any(i.size for i, _ in rows)
        if one_based is True:
            if has_feats and min_idx < 1:
                raise MXNetError(
                    f"one_based=True but found feature index {min_idx}")
            rows = [(i - 1, v) for i, v in rows]
        elif one_based is None and max_idx >= self._n_feat \
                and min_idx >= 1 and max_idx == self._n_feat:
            import warnings

            warnings.warn(
                "LibSVMIter: max feature index equals n_feat; assuming a "
                "1-based file and shifting indices.  Pass one_based=True/"
                "False to silence this heuristic.", stacklevel=2)
            rows = [(i - 1, v) for i, v in rows]
        max_idx = max((int(i.max()) for i, _ in rows if i.size), default=0)
        if max_idx >= self._n_feat:
            raise MXNetError(
                f"libsvm feature index {max_idx} out of range for "
                f"data_shape {data_shape}")
        self._rows = rows
        self._labels = np.asarray(labels, np.float32)
        self._round = round_batch
        self._pos = 0

    @property
    def provide_data(self):
        return [DataDesc(name="data",
                         shape=(self.batch_size, self._n_feat))]

    @property
    def provide_label(self):
        return [DataDesc(name="softmax_label", shape=(self.batch_size,))]

    def reset(self):
        self._pos = 0

    def next(self):
        from ..ndarray import array as nd_array

        n = len(self._rows)
        if self._pos >= n:
            raise StopIteration
        idxs = list(range(self._pos, min(self._pos + self.batch_size, n)))
        pad = self.batch_size - len(idxs)
        if pad:
            if not self._round:
                raise StopIteration
            idxs += list(range(pad))  # wrap-around, reference round_batch
        self._pos += self.batch_size
        dense = np.zeros((self.batch_size, self._n_feat), np.float32)
        for r, j in enumerate(idxs):
            ci, cv = self._rows[j]
            dense[r, ci] = cv
        label = self._labels[idxs]
        csr = nd_array(dense).tostype("csr")
        return DataBatch([csr], [nd_array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False


class _ThreadedIter(DataIter):
    """Shared background-production discipline for prefetching iterators
    (reference: io.py threadediter).  Guarantees the wrappers ride on:

    * a worker failure propagates to the consumer EXACTLY ONCE with the
      worker's original traceback (subsequent ``next()`` raise
      StopIteration until ``reset()``);
    * the worker catches BaseException — a dying worker always leaves a
      message in the queue, so the consumer can never block forever on a
      silently dead thread (the old ``except Exception`` swallowed e.g.
      KeyboardInterrupt and hung the consumer);
    * ``reset()`` restarts cleanly from ANY state — mid-epoch, after
      exhaustion, after a worker error — via a generation counter: the
      old worker is retired (it checks the generation around every
      blocking queue operation), joined, and only then is the wrapped
      iterator reset for the fresh worker.
    """

    _QUEUE_DEPTH = 2

    def __init__(self, inner, batch_size=0):
        super().__init__(batch_size)
        self._iter = inner
        self._gen = 0
        self._done = False
        import queue

        self._queue: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._thread = None
        self._start()

    # -- hooks -------------------------------------------------------------
    def _produce(self):
        """Produce the next item (worker thread); raise StopIteration at
        epoch end."""
        raise NotImplementedError

    def _on_epoch_end(self):
        """Consumer-side hook when the epoch's 'done' marker is consumed."""

    # -- machinery ---------------------------------------------------------
    def _start(self):
        import threading

        gen, q = self._gen, self._queue

        def _put(kind, payload):
            # bounded put that never deadlocks against a consumer that
            # already reset(): a stale-generation worker just drops out
            import queue as _q

            while gen == self._gen:
                try:
                    q.put((gen, kind, payload), timeout=0.1)
                    return True
                except _q.Full:
                    continue
            return False

        def worker():
            while gen == self._gen:
                try:
                    item = self._produce()
                except StopIteration:
                    _put("done", None)
                    return
                except BaseException as exc:  # noqa: BLE001 — see class doc
                    _put("error", exc)
                    return
                if not _put("batch", item):
                    return

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        import queue

        self._gen += 1  # retire the current worker at its next gen check
        thread = self._thread
        while thread is not None and thread.is_alive():
            try:  # unblock a worker parked on a full queue
                self._queue.get(timeout=0.05)
            except queue.Empty:
                pass
        if thread is not None:
            thread.join()
        # only after the old worker is gone may the wrapped iterator be
        # touched — two workers interleaving .next() on one iter would
        # shuffle (or double-consume) batches
        self._iter.reset()
        self._done = False
        self._queue = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._start()

    def next(self):
        import queue as _q

        if self._done:
            raise StopIteration  # repeatable after exhaustion/error
        while True:
            try:
                gen, kind, payload = self._queue.get(timeout=0.1)
            except _q.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # belt and braces: a worker can no longer die without
                    # queueing a marker, but never hang the consumer if
                    # one somehow does
                    self._done = True
                    raise MXNetError(
                        "prefetch worker died without producing a result")
                continue
            if gen != self._gen:
                continue  # stale item from a retired worker
            if kind == "done":
                self._done = True
                self._on_epoch_end()
                raise StopIteration
            if kind == "error":
                self._done = True  # exactly once; then StopIteration
                raise payload  # original worker traceback rides along
            return payload

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label


class PrefetchingIter(_ThreadedIter):
    """Background-thread prefetch wrapper (reference: io.py
    PrefetchingIter over threadediter) — overlaps host-side batch prep
    with device compute, the python analog of the C++ PrefetcherIter.

    rename_data/rename_label: list with one dict mapping original
    descriptor names to new names (reference semantics for binding under
    different arg names).
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here wraps exactly one iter; "
                             "compose multiple with a zip-style wrapper")
        self._rename_data = (rename_data[0] if rename_data else None)
        self._rename_label = (rename_label[0] if rename_label else None)
        super().__init__(iters[0],
                         batch_size=getattr(iters[0], "batch_size", 0))

    def _produce(self):
        return self._iter.next()

    def _renamed(self, descs, mapping):
        if not mapping:
            return descs
        return [DataDesc(name=mapping.get(d.name, d.name), shape=d.shape)
                for d in descs]

    @property
    def provide_data(self):
        return self._renamed(self._iter.provide_data, self._rename_data)

    @property
    def provide_label(self):
        return self._renamed(self._iter.provide_label, self._rename_label)


def _staged_batch_arrays(it):
    """memwatch provider: device arrays of batches parked in the prefetch
    queue (staged but not yet consumed by a step)."""
    out = []
    try:
        items = list(it._queue.queue)
    except Exception:
        return out
    for item in items:
        if not (isinstance(item, tuple) and len(item) == 3):
            continue
        _gen, kind, payload = item
        if kind != "batch" or payload is None:
            continue
        for nd in list(getattr(payload, "data", None) or ()) + \
                list(getattr(payload, "label", None) or ()):
            data = getattr(nd, "_data", None)
            if data is not None:
                out.append(data)
    return out


class DevicePrefetchIter(_ThreadedIter):
    """Device-side input prefetch: wraps any DataIter and stages the NEXT
    batch onto a ``DataParallelStep``'s input shardings (via its
    ``stage()``, i.e. ``_global_put``) from a background thread while the
    current step computes — so the H2D transfer overlaps device compute
    instead of serializing in ``step()``.  The step recognizes the
    pre-placed inputs by their sharding and skips its own transfer
    (telemetry reports the staged bytes as ``h2d_overlapped``).

    Epoch end drains the step's in-flight window: by the time
    StopIteration reaches the training loop every dispatched step has
    landed (and any deferred failure has surfaced).

    Only the FIRST label array is staged (the fused step consumes one
    label); extra label arrays pass through untouched.

    ``depth=None`` (default) sizes the staging queue automatically: 1
    normally, or the superstep group size when ``MX_SUPERSTEP`` is
    active on this step's mesh — a K-step scan dispatch consumes K
    staged batches at once, and a depth-1 queue would stall the group
    fill behind each step's H2D.
    """

    def __init__(self, data_iter, step, depth=None):
        if depth is None:
            from ..parallel.data_parallel import superstep_k

            depth = max(1, superstep_k(getattr(step, "mesh", None)))
        self._step = step
        self._QUEUE_DEPTH = max(1, int(depth))
        super().__init__(data_iter,
                         batch_size=getattr(data_iter, "batch_size", 0))
        # live-array census: batches staged on device ahead of the step
        # are the "inflight" slice of the memory watchdog
        from .. import memwatch

        memwatch.register("inflight", self, _staged_batch_arrays)

    def _produce(self):
        batch = self._iter.next()
        data = list(batch.data or [])
        label = list(batch.label or [])
        staged_data, staged_label = self._step.stage(
            tuple(data), label[0] if label else None)
        return DataBatch(list(staged_data),
                         ([staged_label] + label[1:]) if label else None,
                         pad=batch.pad, index=batch.index,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _on_epoch_end(self):
        self._step.drain()


def stage_batches(iterable, step, depth=None):
    """Generator wrapper giving any (data, ..., label)-tuple iterable —
    e.g. a ``gluon.data.DataLoader`` — the same background device staging
    as :class:`DevicePrefetchIter`: each batch's arrays are pre-placed
    onto ``step``'s input shardings in a worker thread while the previous
    step computes.  Batches that are a single array stage as data only;
    sequences stage all-but-last as data and the last element as label.
    The step's in-flight window is drained when the iterable ends.
    ``depth=None`` auto-sizes to the superstep group size like
    :class:`DevicePrefetchIter`."""
    import queue as _q
    import threading

    if depth is None:
        from ..parallel.data_parallel import superstep_k

        depth = max(1, superstep_k(getattr(step, "mesh", None)))

    q: "_q.Queue" = _q.Queue(maxsize=max(1, int(depth)))
    _END, _ERR = object(), object()
    retired = threading.Event()

    def _put(item):
        # bounded put that never deadlocks against a consumer that
        # abandoned the generator early (same escape as _ThreadedIter's):
        # a retired worker drops out instead of pinning the staged device
        # arrays + this thread forever
        while not retired.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _q.Full:
                continue
        return False

    def worker():
        try:
            for batch in iterable:
                if isinstance(batch, (list, tuple)) and len(batch) >= 2:
                    data, lab = tuple(batch[:-1]), batch[-1]
                    staged, slab = step.stage(data, lab)
                    out = list(staged) + [slab]
                    item = tuple(out) if isinstance(batch, tuple) else out
                else:
                    one = batch[0] if isinstance(batch, (list, tuple)) \
                        else batch
                    staged, _ = step.stage(one, None)
                    item = ([staged[0]] if isinstance(batch, list) else
                            (staged if isinstance(batch, tuple)
                             else staged[0]))
                if not _put(item):
                    return
        except BaseException as exc:  # noqa: BLE001 — consumer re-raises
            _put((_ERR, exc))
            return
        _put((_END, None))

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            # control markers compare by IDENTITY: a real 2-tuple batch
            # holds NDArrays whose == is elementwise and must never be
            # invoked here
            if type(item) is tuple and len(item) == 2 and \
                    (item[0] is _END or item[0] is _ERR):
                if item[0] is _ERR:
                    raise item[1]
                return
            yield item
    finally:
        # runs on normal end, on the error re-raise, AND on generator
        # close/abandonment: retire the worker, then land every in-flight
        # step so nothing is left pending behind the caller's back
        retired.set()
        step.drain()
