"""Core data-iterator API (reference: python/mxnet/io/io.py)."""
from __future__ import annotations

from collections import namedtuple
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "CSVIter", "LibSVMIter", "PrefetchingIter", "DevicePrefetchIter",
           "stage_batches"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return (f"DataDesc[{self.name},{self.shape},{self.dtype},"
                f"{self.layout}]")

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("data must be a list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise MXNetError("label must be a list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return (f"{type(self).__name__}: data shapes: {data_shapes} "
                f"label shapes: {label_shapes}")


class DataIter:
    """Base iterator (reference ~L200)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    from ..ndarray import NDArray

    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError(
            f"Input must be NDArray, numpy.ndarray, list or dict; got "
            f"{type(data)}")
    return list(data.items())


class NDArrayIter(DataIter):
    """Iterator over in-memory arrays (reference ~L600)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self._size())
        if shuffle:
            np.random.shuffle(self.idx)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        num = self._size()
        if last_batch_handle == "discard":
            self.num_data = (num // batch_size) * batch_size
        else:
            self.num_data = num

    def _size(self):
        k, v = self.data[0]
        return len(v)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _take(self, arrays):
        from .. import ndarray as nd
        from ..ndarray import NDArray

        out = []
        for _, v in arrays:
            vnp = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
            end = self.cursor + self.batch_size
            sel = self.idx[self.cursor: end]
            part = vnp[sel]
            if len(part) < self.batch_size:  # pad by wrapping
                if self.last_batch_handle == "pad":
                    extra = vnp[self.idx[: self.batch_size - len(part)]]
                    part = np.concatenate([part, extra])
                elif self.last_batch_handle == "roll_over":
                    pass
            out.append(nd.array(part, dtype=part.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference ~L300)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """libsvm-format iterator yielding csr batches (reference:
    src/io/iter_libsvm.cc).  Rows are kept as (indices, values) pairs —
    only one batch is ever densified (batch_size x n_feat), so huge
    feature spaces don't blow up host memory.

    Indexing: pass one_based=True for 1-based files (liblinear/svmlight
    convention) or one_based=False for 0-based.  The default (None) keeps
    the legacy heuristic — shift when the max index equals n_feat (it would
    be out of range 0-based) — but warns when it triggers, because a
    1-based file that never uses the last feature id is indistinguishable
    from a 0-based one (r3 advisor finding).
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, round_batch=True, one_based=None, **kwargs):
        super().__init__(batch_size)
        self._n_feat = int(np.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                pairs = [p.split(":") for p in parts[1:]]
                rows.append((np.array([int(k) for k, _ in pairs], np.int64),
                             np.array([float(v) for _, v in pairs],
                                      np.float32)))
        if label_libsvm is not None:
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        labels.append(float(line.split()[0]))
        max_idx = max((int(i.max()) for i, _ in rows if i.size), default=0)
        min_idx = min((int(i.min()) for i, _ in rows if i.size), default=0)
        has_feats = any(i.size for i, _ in rows)
        if one_based is True:
            if has_feats and min_idx < 1:
                raise MXNetError(
                    f"one_based=True but found feature index {min_idx}")
            rows = [(i - 1, v) for i, v in rows]
        elif one_based is None and max_idx >= self._n_feat \
                and min_idx >= 1 and max_idx == self._n_feat:
            import warnings

            warnings.warn(
                "LibSVMIter: max feature index equals n_feat; assuming a "
                "1-based file and shifting indices.  Pass one_based=True/"
                "False to silence this heuristic.", stacklevel=2)
            rows = [(i - 1, v) for i, v in rows]
        max_idx = max((int(i.max()) for i, _ in rows if i.size), default=0)
        if max_idx >= self._n_feat:
            raise MXNetError(
                f"libsvm feature index {max_idx} out of range for "
                f"data_shape {data_shape}")
        self._rows = rows
        self._labels = np.asarray(labels, np.float32)
        self._round = round_batch
        self._pos = 0

    @property
    def provide_data(self):
        return [DataDesc(name="data",
                         shape=(self.batch_size, self._n_feat))]

    @property
    def provide_label(self):
        return [DataDesc(name="softmax_label", shape=(self.batch_size,))]

    def reset(self):
        self._pos = 0

    def next(self):
        from ..ndarray import array as nd_array

        n = len(self._rows)
        if self._pos >= n:
            raise StopIteration
        idxs = list(range(self._pos, min(self._pos + self.batch_size, n)))
        pad = self.batch_size - len(idxs)
        if pad:
            if not self._round:
                raise StopIteration
            idxs += list(range(pad))  # wrap-around, reference round_batch
        self._pos += self.batch_size
        dense = np.zeros((self.batch_size, self._n_feat), np.float32)
        for r, j in enumerate(idxs):
            ci, cv = self._rows[j]
            dense[r, ci] = cv
        label = self._labels[idxs]
        csr = nd_array(dense).tostype("csr")
        return DataBatch([csr], [nd_array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False


class _ThreadedIter(DataIter):
    """Shared background-production discipline for prefetching iterators
    (reference: io.py threadediter).  Guarantees the wrappers ride on:

    * a worker failure propagates to the consumer EXACTLY ONCE with the
      worker's original traceback (subsequent ``next()`` raise
      StopIteration until ``reset()``);
    * the worker catches BaseException — a dying worker always leaves a
      message in the queue, so the consumer can never block forever on a
      silently dead thread (the old ``except Exception`` swallowed e.g.
      KeyboardInterrupt and hung the consumer);
    * ``reset()`` restarts cleanly from ANY state — mid-epoch, after
      exhaustion, after a worker error — via a generation counter: the
      old worker is retired (it checks the generation around every
      blocking queue operation), joined, and only then is the wrapped
      iterator reset for the fresh worker.
    """

    _QUEUE_DEPTH = 2

    def __init__(self, inner, batch_size=0):
        super().__init__(batch_size)
        self._iter = inner
        self._gen = 0
        self._done = False
        import queue

        self._queue: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._thread = None
        self._start()

    # -- hooks -------------------------------------------------------------
    def _produce(self):
        """Produce the next item (worker thread); raise StopIteration at
        epoch end."""
        raise NotImplementedError

    def _on_epoch_end(self):
        """Consumer-side hook when the epoch's 'done' marker is consumed."""

    # -- machinery ---------------------------------------------------------
    def _start(self):
        import threading

        gen, q = self._gen, self._queue

        def _put(kind, payload):
            # bounded put that never deadlocks against a consumer that
            # already reset(): a stale-generation worker just drops out
            import queue as _q

            while gen == self._gen:
                try:
                    q.put((gen, kind, payload), timeout=0.1)
                    return True
                except _q.Full:
                    continue
            return False

        def worker():
            while gen == self._gen:
                try:
                    item = self._produce()
                except StopIteration:
                    _put("done", None)
                    return
                except BaseException as exc:  # noqa: BLE001 — see class doc
                    _put("error", exc)
                    return
                if not _put("batch", item):
                    return

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        import queue

        self._gen += 1  # retire the current worker at its next gen check
        thread = self._thread
        while thread is not None and thread.is_alive():
            try:  # unblock a worker parked on a full queue
                self._queue.get(timeout=0.05)
            except queue.Empty:
                pass
        if thread is not None:
            thread.join()
        # only after the old worker is gone may the wrapped iterator be
        # touched — two workers interleaving .next() on one iter would
        # shuffle (or double-consume) batches
        self._iter.reset()
        self._done = False
        self._queue = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._start()

    def next(self):
        import queue as _q

        if self._done:
            raise StopIteration  # repeatable after exhaustion/error
        while True:
            try:
                gen, kind, payload = self._queue.get(timeout=0.1)
            except _q.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # belt and braces: a worker can no longer die without
                    # queueing a marker, but never hang the consumer if
                    # one somehow does
                    self._done = True
                    raise MXNetError(
                        "prefetch worker died without producing a result")
                continue
            if gen != self._gen:
                continue  # stale item from a retired worker
            if kind == "done":
                self._done = True
                self._on_epoch_end()
                raise StopIteration
            if kind == "error":
                self._done = True  # exactly once; then StopIteration
                raise payload  # original worker traceback rides along
            return payload

    def iter_next(self):
        try:
            self.current_batch = self.next()
            return True
        except StopIteration:
            return False

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label


class PrefetchingIter(_ThreadedIter):
    """Background-thread prefetch wrapper (reference: io.py
    PrefetchingIter over threadediter) — overlaps host-side batch prep
    with device compute, the python analog of the C++ PrefetcherIter.

    rename_data/rename_label: list with one dict mapping original
    descriptor names to new names (reference semantics for binding under
    different arg names).
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here wraps exactly one iter; "
                             "compose multiple with a zip-style wrapper")
        self._rename_data = (rename_data[0] if rename_data else None)
        self._rename_label = (rename_label[0] if rename_label else None)
        super().__init__(iters[0],
                         batch_size=getattr(iters[0], "batch_size", 0))

    def _produce(self):
        return self._iter.next()

    def _renamed(self, descs, mapping):
        if not mapping:
            return descs
        return [DataDesc(name=mapping.get(d.name, d.name), shape=d.shape)
                for d in descs]

    @property
    def provide_data(self):
        return self._renamed(self._iter.provide_data, self._rename_data)

    @property
    def provide_label(self):
        return self._renamed(self._iter.provide_label, self._rename_label)


def _staged_batch_arrays(it):
    """memwatch provider: device arrays of batches parked in the prefetch
    queue (staged but not yet consumed by a step)."""
    out = []
    try:
        items = list(it._queue.queue)
    except Exception:
        return out
    for item in items:
        if not (isinstance(item, tuple) and len(item) == 3):
            continue
        _gen, kind, payload = item
        if kind != "batch" or payload is None:
            continue
        for nd in list(getattr(payload, "data", None) or ()) + \
                list(getattr(payload, "label", None) or ()):
            data = getattr(nd, "_data", None)
            if data is not None:
                out.append(data)
    return out


class DevicePrefetchIter(_ThreadedIter):
    """Device-side input prefetch: wraps any DataIter and stages the NEXT
    batch onto a ``DataParallelStep``'s input shardings (via its
    ``stage()``, i.e. ``_global_put``) from a background thread while the
    current step computes — so the H2D transfer overlaps device compute
    instead of serializing in ``step()``.  The step recognizes the
    pre-placed inputs by their sharding and skips its own transfer
    (telemetry reports the staged bytes as ``h2d_overlapped``).

    Epoch end drains the step's in-flight window: by the time
    StopIteration reaches the training loop every dispatched step has
    landed (and any deferred failure has surfaced).

    Only the FIRST label array is staged (the fused step consumes one
    label); extra label arrays pass through untouched.

    ``depth=None`` (default) sizes the staging queue automatically: 1
    normally, or the superstep group size when ``MX_SUPERSTEP`` is
    active on this step's mesh — a K-step scan dispatch consumes K
    staged batches at once, and a depth-1 queue would stall the group
    fill behind each step's H2D.
    """

    def __init__(self, data_iter, step, depth=None):
        if depth is None:
            from ..parallel.data_parallel import superstep_k

            depth = max(1, superstep_k(getattr(step, "mesh", None)))
        self._step = step
        self._QUEUE_DEPTH = max(1, int(depth))
        super().__init__(data_iter,
                         batch_size=getattr(data_iter, "batch_size", 0))
        # live-array census: batches staged on device ahead of the step
        # are the "inflight" slice of the memory watchdog
        from .. import memwatch

        memwatch.register("inflight", self, _staged_batch_arrays)

    def _produce(self):
        batch = self._iter.next()
        data = list(batch.data or [])
        label = list(batch.label or [])
        staged_data, staged_label = self._step.stage(
            tuple(data), label[0] if label else None)
        return DataBatch(list(staged_data),
                         ([staged_label] + label[1:]) if label else None,
                         pad=batch.pad, index=batch.index,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _on_epoch_end(self):
        self._step.drain()


def stage_batches(iterable, step, depth=None):
    """Generator wrapper giving any (data, ..., label)-tuple iterable —
    e.g. a ``gluon.data.DataLoader`` — the same background device staging
    as :class:`DevicePrefetchIter`: each batch's arrays are pre-placed
    onto ``step``'s input shardings in a worker thread while the previous
    step computes.  Batches that are a single array stage as data only;
    sequences stage all-but-last as data and the last element as label.
    The step's in-flight window is drained when the iterable ends.
    ``depth=None`` auto-sizes to the superstep group size like
    :class:`DevicePrefetchIter`."""
    import queue as _q
    import threading

    if depth is None:
        from ..parallel.data_parallel import superstep_k

        depth = max(1, superstep_k(getattr(step, "mesh", None)))

    q: "_q.Queue" = _q.Queue(maxsize=max(1, int(depth)))
    _END, _ERR = object(), object()
    retired = threading.Event()

    def _put(item):
        # bounded put that never deadlocks against a consumer that
        # abandoned the generator early (same escape as _ThreadedIter's):
        # a retired worker drops out instead of pinning the staged device
        # arrays + this thread forever
        while not retired.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _q.Full:
                continue
        return False

    def worker():
        try:
            for batch in iterable:
                if isinstance(batch, (list, tuple)) and len(batch) >= 2:
                    data, lab = tuple(batch[:-1]), batch[-1]
                    staged, slab = step.stage(data, lab)
                    out = list(staged) + [slab]
                    item = tuple(out) if isinstance(batch, tuple) else out
                else:
                    one = batch[0] if isinstance(batch, (list, tuple)) \
                        else batch
                    staged, _ = step.stage(one, None)
                    item = ([staged[0]] if isinstance(batch, list) else
                            (staged if isinstance(batch, tuple)
                             else staged[0]))
                if not _put(item):
                    return
        except BaseException as exc:  # noqa: BLE001 — consumer re-raises
            _put((_ERR, exc))
            return
        _put((_END, None))

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            # control markers compare by IDENTITY: a real 2-tuple batch
            # holds NDArrays whose == is elementwise and must never be
            # invoked here
            if type(item) is tuple and len(item) == 2 and \
                    (item[0] is _END or item[0] is _ERR):
                if item[0] is _ERR:
                    raise item[1]
                return
            yield item
    finally:
        # runs on normal end, on the error re-raise, AND on generator
        # close/abandonment: retire the worker, then land every in-flight
        # step so nothing is left pending behind the caller's back
        retired.set()
        step.drain()
