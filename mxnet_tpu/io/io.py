"""Core data-iterator API (reference: python/mxnet/io/io.py)."""
from __future__ import annotations

from collections import namedtuple
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "CSVIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return (f"DataDesc[{self.name},{self.shape},{self.dtype},"
                f"{self.layout}]")

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("data must be a list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise MXNetError("label must be a list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return (f"{type(self).__name__}: data shapes: {data_shapes} "
                f"label shapes: {label_shapes}")


class DataIter:
    """Base iterator (reference ~L200)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    from ..ndarray import NDArray

    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError(
            f"Input must be NDArray, numpy.ndarray, list or dict; got "
            f"{type(data)}")
    return list(data.items())


class NDArrayIter(DataIter):
    """Iterator over in-memory arrays (reference ~L600)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self._size())
        if shuffle:
            np.random.shuffle(self.idx)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        num = self._size()
        if last_batch_handle == "discard":
            self.num_data = (num // batch_size) * batch_size
        else:
            self.num_data = num

    def _size(self):
        k, v = self.data[0]
        return len(v)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(np.shape(v)[1:]),
                         getattr(v, "dtype", np.float32))
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _take(self, arrays):
        from .. import ndarray as nd
        from ..ndarray import NDArray

        out = []
        for _, v in arrays:
            vnp = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
            end = self.cursor + self.batch_size
            sel = self.idx[self.cursor: end]
            part = vnp[sel]
            if len(part) < self.batch_size:  # pad by wrapping
                if self.last_batch_handle == "pad":
                    extra = vnp[self.idx[: self.batch_size - len(part)]]
                    part = np.concatenate([part, extra])
                elif self.last_batch_handle == "roll_over":
                    pass
            out.append(nd.array(part, dtype=part.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference ~L300)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(DataIter):
    """CSV file iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label
