"""Data iterators (reference: python/mxnet/io/io.py — DataIter, DataBatch,
DataDesc, NDArrayIter ~L600, MXDataIter ~L800; backed by src/io/ iterators).

The C++ RecordIO image pipeline (ImageRecordIter) plugs in via
mxnet_tpu.io.image_iter once the native extension is built; NDArrayIter and
CSVIter are pure Python/jax.
"""
from .io import (DataIter, DataBatch, DataDesc, NDArrayIter, ResizeIter,
                 CSVIter, LibSVMIter, PrefetchingIter, DevicePrefetchIter,
                 stage_batches)
from .image_iter import ImageRecordIter
