"""Weight initializers.

Reference parity: python/mxnet/initializer.py (Initializer registry, Uniform,
Normal, Xavier, MSRAPrelu, Orthogonal, Bilinear, One/Zero/Constant).
"""
from __future__ import annotations

import math
import re
from typing import Optional

import numpy as np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Constant", "Zero", "One",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "register", "create"]

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(init, **kwargs) -> "Initializer":
    if init is None:
        return Uniform(0.07)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        if init.startswith("["):
            # Initializer.dumps() format: '["name", {kwargs}]' (reference:
            # the __init__ variable attr round-trip)
            import json

            name, kw = json.loads(init)
            return create(name, **kw)
        key = init.lower()
        if key not in _REGISTRY:
            raise MXNetError(f"unknown initializer {init!r}")
        return _REGISTRY[key](**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer; subclasses fill a numpy array in-place.

    Using host-side numpy (then device_put) keeps initialization independent
    of the RNG key chain used by sampling ops, like the reference's separate
    initializer RNG.
    """

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        """'["name", {kwargs}]' (reference: Initializer.dumps)."""
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def init_array(self, name: str, shape, dtype) -> np.ndarray:
        from .base import dtype_np

        arr = np.zeros(shape, dtype=np.float32)
        name = name or ""
        if name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif "running_mean" in name or "moving_mean" in name:
            arr[:] = 0.0
        elif "running_var" in name or "moving_var" in name:
            arr[:] = 1.0
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        else:
            self._init_weight(name, arr)
        return arr.astype(dtype_np(dtype))

    def __call__(self, name, arr):  # legacy API: fills an NDArray
        out = self.init_array(name, arr.shape, np.float32)
        arr._set_data(__import__("jax").device_put(out, arr.context.jax_device))

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Uniform(Initializer):
    def __init__(self, scale: float = 0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma: float = 0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register
class Zero(Constant):
    def __init__(self):
        super(Constant, self).__init__()
        self.value = 0.0


@register
class One(Constant):
    def __init__(self):
        super(Constant, self).__init__()
        self.value = 1.0


@register
class Xavier(Initializer):
    """Reference: initializer.py Xavier (rnd_type, factor_type, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier initializer cannot init {name} with shape {shape}")
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, arr.shape)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, arr.shape)
        else:
            raise MXNetError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope**2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        arr[num_hidden : 2 * num_hidden] = self.forget_bias

    def _init_bias(self, name, arr):
        self._init_weight(name, arr)


# string aliases used throughout Gluon layer defaults (reference registers
# Zero as "zeros", One as "ones")
_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


class Mixed:
    """Pattern-dispatched initializer (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must match")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def init_array(self, name, shape, dtype):
        for pat, init in self.map:
            if pat.match(name):
                return init.init_array(name, shape, dtype)
        raise MXNetError(f"parameter {name} did not match any pattern")
