"""Device contexts.

Reference parity: python/mxnet/context.py (Context, mx.cpu()/mx.gpu(i),
thread-local default context, num_gpus ~L1-300).

TPU-native mapping:
  * ``mx.tpu(i)``  -> i-th accelerator device reported by jax (the north-star
    first-class context from BASELINE.json).
  * ``mx.gpu(i)``  -> alias of ``mx.tpu(i)``: reference scripts that say
    ``mx.gpu(0)`` should run unmodified on the accelerator that is present.
  * ``mx.cpu(i)``  -> i-th jax CPU device (host).
  * ``mx.cpu_pinned()`` -> host CPU (PjRt manages pinned staging internally).

A Context is resolved lazily to a ``jax.Device`` so importing mxnet_tpu does
not force backend initialization (tests re-point jax at a virtual CPU mesh
before first use).
"""
from __future__ import annotations

import threading
from typing import List, Optional

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "cpu_pinned",
    "current_context",
    "num_gpus",
    "num_tpus",
    "pin_platform",
    "normalize_memory_stats",
]


def normalize_memory_stats(raw) -> dict:
    """Normalize a PjRt ``Device.memory_stats()`` result to a stable
    schema: ``{"bytes_in_use", "peak_bytes_in_use", "bytes_limit",
    "available"}``.

    PjRt's dict is backend-dependent (TPU/GPU expose the TCMalloc-style
    allocator counters; XLA:CPU returns ``None``), and the raw shape was
    leaking to callers — ``Context.memory_stats()`` used to hand back the
    raw dict or a silent ``None``.  The CPU fallback is documented:
    ``available=False`` with zeroed counters, so callers branch on ONE
    flag instead of probing for keys; ``mxnet_tpu.memwatch`` then derives
    usage from the ``jax.live_arrays()`` census instead.  A dict without
    ``bytes_in_use`` counts as unavailable too — all-zero counters must
    never masquerade as a real reading."""
    if not isinstance(raw, dict) or "bytes_in_use" not in raw:
        return {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                "bytes_limit": 0, "available": False}

    def _int(key, default=0):
        try:
            return int(raw.get(key, default))
        except (TypeError, ValueError):
            return default

    in_use = _int("bytes_in_use")
    return {"bytes_in_use": in_use,
            "peak_bytes_in_use": _int("peak_bytes_in_use", in_use),
            "bytes_limit": _int("bytes_limit"),
            "available": True}

_ACCEL_TYPES = ("tpu", "gpu")


def _jax():
    import jax

    return jax


def pin_platform(name: str) -> None:
    """Pin the jax backend platform (e.g. "cpu") before first device touch.

    The ONE sanctioned mechanism: setting the JAX_PLATFORMS env var is NOT
    reliable when a TPU-relay shim intercepts backend lookup (it can still
    hang on a dead relay); jax.config.update always takes effect as long as
    no device has been touched yet.  Used by examples, bench.py and tools.
    """
    _jax().config.update("jax_platforms", name)


class Context:
    """A device context; compares by (device_type, device_id)."""

    _default_ctx = threading.local()
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "cpu_shared", 5: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devstr2type:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)
        self._old_ctx: Optional["Context"] = None

    # -- identity ----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- jax resolution ----------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device (lazy; raises if absent)."""
        jax = _jax()
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            # LOCAL devices: under multi-process SPMD, jax.devices() lists
            # every host's devices; a context must resolve to one this
            # process can address (reference semantics: each worker sees
            # only its own devices).
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                # Platform list restricted (e.g. JAX_PLATFORMS=axon): fall back
                # to the default backend so cpu-context code still runs.
                devs = jax.local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        # accelerator: gpu is an alias for whatever accelerator jax exposes
        devs = _accel_devices()
        if not devs:
            raise MXNetError(
                f"{self} requested but no accelerator device is visible to jax"
            )
        if self.device_id >= len(devs):
            raise MXNetError(f"{self} out of range: {len(devs)} device(s) visible")
        return devs[self.device_id]

    # -- scope -------------------------------------------------------------
    def __enter__(self):
        self._old_ctx = current_context()
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx
        return False

    def empty_cache(self):
        """Reference: mx.context.Context.empty_cache; PjRt pools internally."""

    def memory_stats(self) -> dict:
        """This device's memory stats, normalized to the stable schema of
        :func:`normalize_memory_stats` — never ``None``: backends without
        allocator stats (XLA:CPU) return ``available=False`` with zeroed
        counters (``mxnet_tpu.memwatch`` falls back to the live-array
        census there)."""
        dev = self.jax_device
        stats = getattr(dev, "memory_stats", None)
        raw = None
        if stats is not None:
            try:
                raw = stats()
            except Exception:
                raw = None
        return normalize_memory_stats(raw)


def _accel_devices() -> List:
    jax = _jax()
    devs = jax.local_devices()
    accel = [d for d in devs if d.platform not in ("cpu",)]
    if accel:
        return accel
    # CPU-only process (tests): accelerator contexts map onto host devices so
    # the same model code runs under the virtual device mesh.
    return devs


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Number of accelerator devices (gpu alias — see module docstring)."""
    try:
        return len([d for d in _jax().local_devices() if d.platform != "cpu"])
    except RuntimeError:
        return 0


def num_tpus() -> int:
    return num_gpus()


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value") or Context._default_ctx.value is None:
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
