"""Subgraph partitioning API.

Reference parity: src/operator/subgraph/ — SubgraphProperty registry
(subgraph_property.h ~L100), the graph partitioner pass
(build_subgraph.cc ~L700), default_subgraph_property.cc, and the
MXNET_SUBGRAPH_BACKEND env hook.  This is the mechanism external backends
(TensorRT/MKLDNN in the reference) use to claim regions of a symbolic
graph as single fused nodes.

TPU-native role: XLA already fuses whole graphs, so the partitioner's value
here is the MECHANISM (parity for tooling that inspects/partitions graphs)
plus per-region jit: each claimed subgraph executes as its own jitted
callable, which also demonstrates the XLA-subgraph backend pattern SURVEY
§2 N25 calls for.

Grouping is cycle-safe: a node may join a candidate group only if no path
from that group re-enters through a non-member node ("poison" sets, the
same invariant build_subgraph.cc enforces with its snake/incomplete
checks).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .base import MXNetError

__all__ = ["SubgraphProperty", "register_subgraph_property",
           "get_subgraph_property", "list_subgraph_backends", "partition"]


class SubgraphProperty:
    """Decides which ops a backend claims (reference: SubgraphProperty).

    Subclass and override op_match (per-node) and optionally
    accept_subgraph (whole-group veto) and min_size.
    """

    name = "base"
    min_size = 2  # singleton groups are not worth a fused node

    def op_match(self, node) -> bool:
        raise NotImplementedError

    def accept_subgraph(self, nodes: Sequence) -> bool:
        return len(nodes) >= self.min_size


# elementwise/compute ops that are always safe to claim: pure, single-output,
# no RNG/aux state (BatchNorm/Dropout stay outside)
_DEFAULT_OPS = {
    "Activation", "relu", "sigmoid", "tanh", "softsign", "exp", "log",
    "sqrt", "square", "negative", "abs", "clip", "LeakyReLU",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "_plus_scalar", "_minus_scalar", "_mul_scalar", "_div_scalar",
    "FullyConnected", "Convolution", "dot", "Flatten", "reshape",
    "transpose", "Concat", "sum", "mean", "max", "min", "softmax",
    "log_softmax",
}


class DefaultSubgraphProperty(SubgraphProperty):
    """Claims maximal regions of pure compute ops (reference:
    default_subgraph_property.cc)."""

    name = "default"

    def op_match(self, node) -> bool:
        return node.op in _DEFAULT_OPS


_PROPERTIES: Dict[str, SubgraphProperty] = {}


def register_subgraph_property(prop: SubgraphProperty) -> None:
    _PROPERTIES[prop.name] = prop


def get_subgraph_property(name: str) -> SubgraphProperty:
    try:
        return _PROPERTIES[name]
    except KeyError:
        raise MXNetError(
            f"unknown subgraph backend {name!r}; registered: "
            f"{sorted(_PROPERTIES)}") from None


def list_subgraph_backends() -> List[str]:
    return sorted(_PROPERTIES)


register_subgraph_property(DefaultSubgraphProperty())


# ---------------------------------------------------------------------------
# partitioner
# ---------------------------------------------------------------------------
def _group_nodes(order, prop):
    """Assign group ids (or None) to op nodes; cycle-safe.

    poison[n]: set of group ids reachable at n through at least one
    non-member node — n must never join those groups (doing so would put a
    non-member on a path between two members, i.e. a cycle in the
    coarsened graph).  Group ids go through a union-find so that poison
    sets recorded BEFORE a merge still name the merged group correctly.
    """
    group: Dict[int, Optional[int]] = {}
    poison: Dict[int, Set[int]] = {}
    gpoison: Dict[int, Set[int]] = {}
    members: Dict[int, List] = {}
    parent_gid: Dict[int, int] = {}
    next_gid = 0

    def find(g: int) -> int:
        while parent_gid[g] != g:
            parent_gid[g] = parent_gid[parent_gid[g]]
            g = parent_gid[g]
        return g

    def canon(gs: Set[int]) -> Set[int]:
        return {find(g) for g in gs}

    for node in order:
        p: Set[int] = set()
        cand: Set[int] = set()
        for par, _ in node.inputs:
            p |= poison.get(id(par), set())
            pg = group.get(id(par))
            if pg is not None:
                cand.add(find(pg))
        p = canon(p)
        my_group = None
        if not node.is_variable() and prop.op_match(node):
            ok = {g for g in cand if g not in p}
            # merging several groups: each must not be poisoned w.r.t. the
            # others
            safe: List[int] = []
            for g in sorted(ok):
                if all(g not in canon(gpoison.get(o, set()))
                       and o not in canon(gpoison.get(g, set()))
                       for o in safe):
                    safe.append(g)
            if safe:
                my_group = safe[0]
                for g in safe[1:]:
                    parent_gid[g] = my_group
                    members[my_group].extend(members.pop(g))
                    gpoison[my_group] |= gpoison.pop(g, set())
                members[my_group].append(node)
            else:
                my_group = next_gid
                next_gid += 1
                parent_gid[my_group] = my_group
                members[my_group] = [node]
                gpoison[my_group] = set()
            group[id(node)] = my_group
            gpoison[my_group] |= p
        else:
            group[id(node)] = None
        # groups whose values flow PAST this node while it is not a member
        # (compare through find(): ids in cand may have just been merged
        # into my_group — poisoning those would wall off our own group)
        mg = find(my_group) if my_group is not None else None
        poison[id(node)] = p | {g for g in cand if find(g) != mg}

    # resolve every node's group to its canonical id
    group = {k: (find(v) if v is not None else None)
             for k, v in group.items()}
    return group, members


def partition(sym, backend_or_prop="default"):
    """Partition a Symbol's graph for a backend; claimed regions become
    single '_subgraph' nodes executing the region as one jitted callable
    (reference: MXOptimizeForBackend / build_subgraph.cc).
    """
    from .symbol.symbol import Symbol, _Node, _topo_order, _apply_node

    prop = (backend_or_prop if isinstance(backend_or_prop, SubgraphProperty)
            else get_subgraph_property(backend_or_prop))
    entries = sym._entries
    order = _topo_order(entries)
    group, members = _group_nodes(order, prop)

    # veto small groups
    for gid in list(members):
        if not prop.accept_subgraph(members[gid]):
            for m in members[gid]:
                group[id(m)] = None
            del members[gid]

    if not members:
        return sym

    member_ids = {id(m): gid for gid, ms in members.items() for m in ms}
    # external inputs (entries from non-members) and outputs (member entries
    # consumed outside, or graph outputs) per group, in deterministic order
    ext_inputs: Dict[int, List[Tuple]] = {g: [] for g in members}
    outputs: Dict[int, List[Tuple]] = {g: [] for g in members}

    def note_input(gid, entry):
        if all(e[0] is not entry[0] or e[1] != entry[1]
               for e in ext_inputs[gid]):
            ext_inputs[gid].append(entry)

    def note_output(gid, entry):
        if all(e[0] is not entry[0] or e[1] != entry[1]
               for e in outputs[gid]):
            outputs[gid].append(entry)

    for node in order:
        gid = member_ids.get(id(node))
        for parent, oi in node.inputs:
            pgid = member_ids.get(id(parent))
            if gid is not None and pgid != gid:
                note_input(gid, (parent, oi))
            if pgid is not None and gid != pgid:
                note_output(pgid, (parent, oi))
    for e in entries:
        pgid = member_ids.get(id(e[0]))
        if pgid is not None:
            note_output(pgid, e)

    def make_subgraph_fn(gid):
        ins = ext_inputs[gid]
        outs = outputs[gid]
        mset = {id(m) for m in members[gid]}
        # close over only this group's nodes (topo order), not the whole
        # pre-partition graph
        member_order = [n for n in order if id(n) in mset]

        def fn(*ext_vals):
            vals: Dict[int, dict] = {}
            for (n, oi), v in zip(ins, ext_vals):
                vals.setdefault(id(n), {})[oi] = v
            for node in member_order:
                node_in = [vals[id(p)][oi] for p, oi in node.inputs]
                out = _apply_node(node, node_in, None, False)
                out = list(out) if isinstance(out, (tuple, list)) else [out]
                vals[id(node)] = dict(enumerate(out))
            return tuple(vals[id(n)][oi] for n, oi in outs)

        return fn

    # rebuild the graph with each group collapsed into one _subgraph node
    memo: Dict[int, _Node] = {}
    gnode: Dict[int, _Node] = {}

    def rebuild_entry(entry):
        node, oi = entry
        gid = member_ids.get(id(node))
        if gid is not None:
            sg = build_group(gid)
            pos = next(i for i, (n, o) in enumerate(outputs[gid])
                       if n is node and o == oi)
            return (sg, pos)
        return (rebuild(node), oi)

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.is_variable():
            memo[id(node)] = node
            return node
        new_inputs = [rebuild_entry(e) for e in node.inputs]
        nn = _Node(node.op, node.name, node.attrs, new_inputs,
                   node.num_outputs, getattr(node, "vattrs", None))
        memo[id(node)] = nn
        return nn

    _building: Set[int] = set()

    def build_group(gid):
        if gid in gnode:
            return gnode[gid]
        if gid in _building:  # defensive: a cycle here is a partitioner bug
            raise MXNetError(
                f"subgraph partition produced a cyclic coarsened graph "
                f"(group {gid}) — please report")
        _building.add(gid)
        new_inputs = [rebuild_entry(e) for e in ext_inputs[gid]]
        nn = _Node("_subgraph", f"{prop.name}_subgraph{gid}",
                   {"fn": make_subgraph_fn(gid),
                    "backend": prop.name,
                    "num_nodes": len(members[gid]),
                    "ops": sorted({m.op for m in members[gid]})},
                   new_inputs, num_outputs=len(outputs[gid]))
        gnode[gid] = nn
        return nn

    return Symbol([rebuild_entry(e) for e in entries])


def env_backend() -> Optional[str]:
    """MXNET_SUBGRAPH_BACKEND env hook (reference: build_subgraph.cc)."""
    name = os.environ.get("MXNET_SUBGRAPH_BACKEND", "").strip()
    return name or None
