"""mx.operator: user-defined operators in Python.

Reference parity: python/mxnet/operator.py (CustomOp/CustomOpProp/register)
over src/operator/custom/custom.cc (~L100: CustomOperator runs Python
callbacks on a dedicated thread pool outside engine workers).

TPU-native design: a custom op runs eagerly on concrete arrays (like the
reference, which exits the engine for the Python callback) and integrates
with autograd through the same tape mechanism as autograd.Function — the
user's backward() is recorded as the node's vjp.  Inside a hybridize/jit
trace custom ops are not traceable (they are opaque Python); CachedOp
graphs containing one fall back to eager, matching the reference's
behavioral contract that Custom breaks bulk execution.
"""
from __future__ import annotations

from typing import Dict, List, Type

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]

_CUSTOM_OPS: Dict[str, Type["CustomOpProp"]] = {}


class CustomOp:
    """Base class for user ops (reference: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write src into dst honoring the grad_req (reference semantics)."""
        if req in ("null", None):
            return
        if req == "add":
            dst._set_data(dst._data + src._data.astype(dst._data.dtype))
        else:  # write / inplace
            dst._set_data(src._data.astype(dst._data.dtype))


class CustomOpProp:
    """Op metadata provider (reference: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name: str):
    """Decorator registering a CustomOpProp subclass under op_type name."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_OPS[reg_name] = prop_cls
        return prop_cls

    return deco


def get_all_registered_operators() -> List[str]:
    return sorted(_CUSTOM_OPS)


def _invoke_custom(op_type: str, inputs, **kwargs):
    """mx.nd.Custom implementation (reference: MXImperativeInvokeEx on the
    'Custom' op -> custom.cc CustomOperator)."""
    from . import autograd
    from .ndarray import NDArray, zeros

    prop_cls = _CUSTOM_OPS.get(op_type)
    if prop_cls is None:
        raise MXNetError(f"unknown custom op type {op_type!r}")
    import inspect

    accepted = inspect.signature(prop_cls.__init__).parameters
    init_kwargs = {k: v for k, v in kwargs.items() if k in accepted}
    prop = prop_cls(**init_kwargs)

    arg_names = prop.list_arguments()
    if len(inputs) != len(arg_names):
        raise MXNetError(f"custom op {op_type}: expected {len(arg_names)} "
                         f"inputs {arg_names}, got {len(inputs)}")
    ctx = inputs[0].context
    in_shapes = [list(x.shape) for x in inputs]
    _, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    op = prop.create_operator(ctx, in_shapes,
                              [x.dtype for x in inputs])

    n_out = len(out_shapes)
    aux = [zeros(tuple(s), ctx=ctx) for s in aux_shapes]

    class _Fn(autograd.Function):
        def forward(self, *in_data):
            out_data = [zeros(tuple(s), ctx=ctx) for s in out_shapes]
            op.forward(is_train=autograd.is_training(),
                       req=["write"] * n_out,
                       in_data=list(in_data), out_data=out_data, aux=aux)
            self._saved = (list(in_data), out_data)
            return out_data[0] if n_out == 1 else tuple(out_data)

        def backward(self, *out_grad):
            in_data, out_data = self._saved
            in_grad = [zeros(x.shape, ctx=ctx, dtype=x.dtype)
                       for x in in_data]
            op.backward(req=["write"] * len(in_data),
                        out_grad=list(out_grad), in_data=in_data,
                        out_data=out_data, in_grad=in_grad, aux=aux)
            return in_grad[0] if len(in_grad) == 1 else tuple(in_grad)

    return _Fn()(*inputs)


def Custom(*args, op_type=None, **kwargs):
    """mx.nd.Custom(*inputs, op_type='name', **op_kwargs)."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    from .ndarray import NDArray, array

    inputs = [a if isinstance(a, NDArray) else array(a) for a in args]
    return _invoke_custom(op_type, inputs, **kwargs)
