"""Symbol attribute scopes (reference: python/mxnet/attribute.py —
AttrScope).  ``with mx.AttrScope(ctx_group='dev1', lr_mult='0.1'):``
attaches the given attributes to every symbol node created in the scope;
nested scopes merge with inner-wins semantics.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings "
                                 "(reference convention)")
        self._attr: Dict[str, str] = dict(kwargs)

    def get(self, attr: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """Scope attrs merged with (and overridden by) explicit `attr`."""
        if not self._attr:
            return dict(attr or {})
        out = dict(self._attr)
        out.update(attr or {})
        return out

    def __enter__(self):
        # stack, not a single slot: reusing one instance in nested/repeated
        # with-blocks must restore correctly
        if not hasattr(self, "_old_stack"):
            self._old_stack = []
        old = getattr(AttrScope._current, "value", None)
        self._old_stack.append(old)
        merged = AttrScope()
        merged._attr = dict((old or _DEFAULT)._attr)
        merged._attr.update(self._attr)
        AttrScope._current.value = merged
        return self

    def __exit__(self, *exc):
        AttrScope._current.value = self._old_stack.pop()
        return False


def current() -> AttrScope:
    cur = getattr(AttrScope._current, "value", None)
    return cur if cur is not None else _DEFAULT


_DEFAULT = AttrScope()
