"""Persistent AOT executable cache: serialize compiled XLA programs to
disk so a restarted process deserializes instead of recompiling
(docs/PERFORMANCE.md §Superstep & AOT executable cache).

Cold-start after a gang restart (tools/launch.py --max-restarts) is a
production SLO: today every rank pays the full trace + XLA compile of its
step/updater programs again — minutes for model-sized programs — before
the first post-restart step dispatches.  This module closes that gap with
ahead-of-time lowering at the jit sites that dominate that wall
(``DataParallelStep`` single-step and superstep executables,
``FusedUpdater`` fused-apply groups): the site lowers explicitly
(``jax.jit(...).lower(*args).compile()``), the compiled executable is
serialized via ``jax.experimental.serialize_executable`` (verified
working on the pinned jax) under ``MX_EXECUTABLE_CACHE_DIR``, and a
restarted process loads the bytes back in milliseconds.

Cache key contract (the reason PR 8 made ``memwatch.fingerprint``
restart-stable): an entry is addressed by

    (memwatch.fingerprint(parts), jax.__version__, platform, mesh shape)

— structural program identity only, never object ids, so the same
program in a restarted process maps to the same entry; a jax upgrade, a
different backend, or a different mesh shape silently misses instead of
loading an incompatible executable.

Failure posture: the cache is an OPTIMIZATION and must never take a
training run down.  Corrupt / truncated / version-mismatched entries,
serialization not supported for a program, unwritable cache directories —
every failure falls back to the normal compile path (logged at debug/
warning, surfaced as ``cache_corrupt`` on the compile telemetry event
where applicable).  ``MX_EXECUTABLE_CACHE=0`` is the kill switch: no
loads, no stores, byte-for-byte the pre-cache behavior.
"""
from __future__ import annotations

import logging
import os
import pickle
import time
from typing import Any, Dict, Optional, Tuple

__all__ = ["enabled", "cache_dir", "cache_key", "entry_path",
           "get_or_compile", "load", "store"]

_LOG = logging.getLogger("mxnet_tpu.aot_cache")

# bumped whenever the on-disk layout changes; a mismatch is a miss
_MAGIC = "MXAOT1"


def enabled() -> bool:
    """AOT persistence is on when ``MX_EXECUTABLE_CACHE_DIR`` names a
    directory and the ``MX_EXECUTABLE_CACHE`` kill switch isn't 0."""
    if not os.environ.get("MX_EXECUTABLE_CACHE_DIR"):
        return False
    return os.environ.get("MX_EXECUTABLE_CACHE", "1").lower() not in (
        "0", "false", "off")


def cache_dir() -> Optional[str]:
    return os.environ.get("MX_EXECUTABLE_CACHE_DIR") or None


def cache_key(fingerprint: str, platform: str,
              mesh_shape: Tuple = (), device_ids: Tuple = ()) -> str:
    """Filename-safe entry key: program fingerprint x jax version x
    backend platform x mesh shape x device assignment.  The fingerprint
    already encodes structural identity (shapes/dtypes/static hypers);
    version/platform/mesh ride alongside explicitly so an incompatible
    executable can never be addressed, only missed.  ``device_ids`` (the
    mesh's global device ids) matter because the serialized executable
    embeds its device assignment: in a gang where ranks run LOCAL
    per-rank meshes, rank 1's program targets global device 1 — rank 0's
    entry would deserialize to an assignment with no local devices.
    Ranks sharing one global SPMD mesh share one key (identical
    assignment), which is the useful sharing."""
    import hashlib

    import jax

    env = hashlib.sha256(
        repr((jax.__version__, platform, tuple(mesh_shape),
              tuple(device_ids))).encode()
    ).hexdigest()[:8]
    return f"{fingerprint}-{env}"


def entry_path(key: str) -> Optional[str]:
    d = cache_dir()
    if d is None:
        return None
    return os.path.join(d, f"{key}.jexec")


def store(key: str, compiled, meta: Optional[Dict[str, Any]] = None) -> bool:
    """Serialize ``compiled`` (a jax.stages.Compiled) under ``key``.
    Atomic (tmp + rename) so a concurrently-restarting rank never reads a
    torn entry; best-effort — failures are logged, never raised."""
    path = entry_path(key)
    if path is None:
        return False
    try:
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = se.serialize(compiled)
        import jax

        blob = pickle.dumps({
            "magic": _MAGIC,
            "jax": jax.__version__,
            "key": key,
            "meta": dict(meta or {}),
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        })
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return True
    except Exception as e:  # the cache must never take training down
        _LOG.warning("aot_cache: failed to store %s: %s", key, e)
        return False


def load(key: str):
    """Deserialize the entry under ``key`` -> (loaded_executable, info)
    or (None, info).  ``info['cache_corrupt']`` marks an entry that
    existed but could not be loaded (truncated, garbled, wrong version) —
    the caller falls back to a fresh compile, which overwrites it."""
    info: Dict[str, Any] = {}
    path = entry_path(key)
    if path is None or not os.path.exists(path):
        return None, info
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            rec = pickle.load(f)
        import jax

        if (not isinstance(rec, dict) or rec.get("magic") != _MAGIC
                or rec.get("jax") != jax.__version__
                or rec.get("key") != key):
            raise ValueError("entry metadata mismatch")
        from jax.experimental import serialize_executable as se

        loaded = se.deserialize_and_load(
            rec["payload"], rec["in_tree"], rec["out_tree"])
        info["cache_hit"] = True
        info["deserialize_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        # caller-supplied meta rides back out: sites whose executables
        # need structural facts the python fn only yields at trace time
        # (CachedOp's output treedef/aux binding) restore them from here
        # instead of paying the trace a cache hit exists to skip
        info["meta"] = rec.get("meta") or {}
        return loaded, info
    except Exception as e:
        # torn write, partial disk, version drift, pickle garbage: all
        # fall back to a fresh compile (which re-stores a clean entry)
        _LOG.warning("aot_cache: corrupt/unloadable entry %s (%s); "
                     "falling back to fresh compile", key, e)
        info["cache_corrupt"] = True
        return None, info


def get_or_compile(jitted, args, fingerprint: str, platform: str,
                   mesh_shape: Tuple = (), device_ids: Tuple = (),
                   meta_fn=None):
    """The jit-site entry point: resolve ``fingerprint`` to a compiled
    executable — deserialized from the persistent cache when warm, else
    compiled ahead-of-time (``jitted.lower(*args).compile()``) and
    stored.  Returns ``(compiled_or_None, info)``; ``None`` means the
    cache is disabled or AOT failed entirely and the caller should fall
    back to calling ``jitted`` directly (the plain jit path).

    ``info`` feeds the compile telemetry event: ``cache_hit`` +
    ``deserialize_ms`` on a warm load, ``cache_hit=False`` (+ optional
    ``cache_corrupt``) after a fresh AOT compile.

    ``meta_fn`` (optional, zero-arg) supplies extra entry metadata and
    is called AFTER the fresh compile — i.e. after ``jitted`` traced,
    so structural facts the trace produces as side effects can be
    captured; on a warm load the stored metadata returns in
    ``info['meta']`` instead."""
    if not enabled():
        return None, {}
    try:
        key = cache_key(fingerprint, platform, mesh_shape, device_ids)
        compiled, info = load(key)
        if meta_fn is None:
            # only sites that persist structural meta consume it; the
            # others forward info verbatim into compile telemetry
            # events, which must not grow a redundant meta blob
            info.pop("meta", None)
        if compiled is not None:
            return compiled, info
        t0 = time.perf_counter()
        compiled = jitted.lower(*args).compile()
        info["cache_hit"] = False
        info["aot_compile_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        meta = {"fingerprint": fingerprint, "platform": platform,
                "mesh_shape": tuple(mesh_shape)}
        if meta_fn is not None:
            meta.update(meta_fn() or {})
        store(key, compiled, meta=meta)
        return compiled, info
    except Exception as e:
        _LOG.warning("aot_cache: AOT compile/load failed for %s (%s); "
                     "falling back to plain jit dispatch", fingerprint, e)
        return None, {}
