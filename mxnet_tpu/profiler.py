"""Profiler facade (reference: python/mxnet/profiler.py over src/profiler/ —
set_config/start/stop/dump, aggregate stats; SURVEY §5.1).

TPU-native: bridges to jax.profiler — start()/stop() capture a TensorBoard/
perfetto trace of XLA execution (the analog of the reference's Chrome
trace), and `scope`/`Task` map onto jax trace annotations.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from .base import MXNetError, env_bool

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "scope", "Task", "Frame", "Marker", "state"]

_config = {"filename": "profile.json", "profile_all": False,
           "trace_dir": None}
_running = False
# one numbered subdirectory per start()/resume() — jax.profiler.start_trace
# into the SAME directory twice clobbers the first trace, so each segment
# gets a fresh dir and dump() lists them all
_segments: list = []

# ---------------------------------------------------------------------------
# aggregate per-op stats (reference: src/profiler/aggregate_stats.cc — the
# table printed by mx.profiler.dumps()).  Populated by the op dispatch layer
# (ops/registry.invoke) and the compiled-step executors while a profile is
# running: on TPU the engine-level hook of the reference
# (ThreadedEngine::ExecuteOprBlock profiler brackets) becomes a hook at the
# two places work is issued — eager op dispatch and jitted step execution.
# ---------------------------------------------------------------------------
_aggregate: dict = {}


def is_recording() -> bool:
    """True while op timings should be collected (profile running)."""
    return _running


def record_op(name: str, seconds: float, memory: int = 0) -> None:
    """Record one execution of `name` (called from the dispatch layer).

    ``memory`` is the peak device bytes observed for this call —
    ``timed_call`` plumbs it from ``mxnet_tpu.memwatch.peak_bytes()``
    whenever ``profile_memory`` (or ``profile_all``) is configured, so
    the reference's ``profile_memory`` flag is no longer a no-op: the
    aggregate keeps the max and ``dumps()`` surfaces a Peak(MB) column /
    ``peak_mem_bytes`` json field."""
    ent = _aggregate.get(name)
    if ent is None:
        _aggregate[name] = [1, seconds, seconds, seconds, memory]
    else:
        ent[0] += 1
        ent[1] += seconds
        ent[2] = min(ent[2], seconds)
        ent[3] = max(ent[3], seconds)
        ent[4] = max(ent[4], memory)


def _profile_memory_on() -> bool:
    return bool(_config.get("profile_memory") or _config.get("profile_all"))


def reset_stats() -> None:
    _aggregate.clear()


def timed_call(name: str, fn, *args, **kwargs):
    """Run fn(*args, **kwargs), block on every jax-array leaf of the result,
    and record the wall time under `name`.  The single shared scaffold for
    all profiled call sites (op dispatch, CachedOp, fused step)."""
    import time as _time

    import jax

    t0 = _time.perf_counter()
    result = fn(*args, **kwargs)
    leaves = [getattr(x, "_data", x) for x in jax.tree_util.tree_leaves(result)]
    jax.block_until_ready([x for x in leaves
                           if not isinstance(x, (int, float, str, bool))])
    dt = _time.perf_counter() - t0
    mem = 0
    if _profile_memory_on():
        # this scaffold already blocked on the result, so the (blocking-
        # context-only) peak probe is in its contract; memwatch prefers
        # PjRt's peak_bytes_in_use and falls back to the live-array total
        from . import memwatch

        try:
            mem = memwatch.peak_bytes()
        except Exception:
            mem = 0
    record_op(name, dt, memory=mem)
    return result


def set_config(**kwargs):
    """Accepts the reference's kwargs (profile_all, profile_symbolic,
    profile_imperative, profile_memory, profile_api, filename, ...)."""
    _config.update(kwargs)
    if "filename" in kwargs:
        base = kwargs["filename"]
        _config["trace_dir"] = os.path.splitext(base)[0] + "_jax_trace"


def _trace_dir():
    if _config["trace_dir"] is None:
        _config["trace_dir"] = "mxnet_tpu_profile"
    return _config["trace_dir"]


def start():
    global _running
    import jax

    if _running:
        return
    segment = os.path.join(_trace_dir(), f"segment-{len(_segments):03d}")
    jax.profiler.start_trace(segment)
    _segments.append(segment)
    _running = True


def stop():
    global _running
    import jax

    if not _running:
        return
    jax.profiler.stop_trace()
    _running = False


def state():
    return "running" if _running else "stopped"


def pause():
    """Suspend tracing; resume() continues into a FRESH numbered segment
    (resuming into the same directory clobbered the prior trace)."""
    stop()


def resume():
    start()


def dump(finished=True, profile_process="worker"):
    """The jax trace is written on stop_trace; this flushes and returns the
    list of trace segment directories captured so far (one per
    start()/resume() cycle)."""
    if _running:
        stop()
    return list(_segments)


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Ranked per-op aggregate table (reference: MXAggregateProfileStatsPrint
    over aggregate_stats.cc) plus the jax trace location.

    format: 'table' (human) or 'json' (machine-readable list of rows).
    The table form also appends the runtime-telemetry rollup and trace
    segment list; the json form stays a bare row list for compatibility —
    machine consumers read the rollup from its first-class API,
    ``mxnet_tpu.telemetry.summary()``, and segments from ``dump()``."""
    if format not in ("table", "json"):
        raise MXNetError(f"unsupported dumps format {format!r}")
    if not _aggregate:
        if format == "json":
            import json as _json

            return _json.dumps([])
        return (f"profile trace directory: {_trace_dir()}\n"
                "(no per-op stats recorded — run ops between profiler."
                "start() and stop())" + _telemetry_rollup_lines())
    key = {"total": lambda e: e[1][1], "count": lambda e: e[1][0],
           "avg": lambda e: e[1][1] / e[1][0], "min": lambda e: e[1][2],
           "max": lambda e: e[1][3]}.get(sort_by, lambda e: e[1][1])
    rows = sorted(_aggregate.items(), key=key, reverse=not ascending)
    has_mem = any(m for _n, (_c, _t, _mn, _mx, m) in rows)
    if format == "json":
        import json as _json

        out = [dict({"name": n, "count": c, "total_ms": t * 1e3,
                     "avg_ms": t / c * 1e3, "min_ms": mn * 1e3,
                     "max_ms": mx * 1e3},
                    **({"peak_mem_bytes": m} if has_mem else {}))
               for n, (c, t, mn, mx, m) in rows]
        if reset:
            reset_stats()
        return _json.dumps(out)
    name_w = max(24, max(len(n) for n, _ in rows) + 2)
    header = (f"{'Name':<{name_w}}{'Calls':>8}{'Total(ms)':>12}"
              f"{'Avg(ms)':>10}{'Min(ms)':>10}{'Max(ms)':>10}")
    if has_mem:
        header += f"{'Peak(MB)':>10}"
    lines = ["Profile Statistics:", header,
             "-" * (name_w + 50 + (10 if has_mem else 0))]
    for name, (count, total, mn, mx, mem) in rows:
        line = (f"{name:<{name_w}}{count:>8}{total * 1e3:>12.3f}"
                f"{total / count * 1e3:>10.3f}{mn * 1e3:>10.3f}"
                f"{mx * 1e3:>10.3f}")
        if has_mem:
            line += f"{mem / 1e6:>10.2f}"
        lines.append(line)
    lines.append(f"\nprofile trace directory: {_trace_dir()}")
    if len(_segments) > 1:
        lines.append("trace segments: " + ", ".join(_segments))
    lines.append(_telemetry_rollup_lines().lstrip("\n"))
    if reset:
        reset_stats()
    return "\n".join(lines)


def _telemetry_rollup_lines() -> str:
    """The runtime-telemetry rollup appended to dumps() so one call answers
    both 'which op is slow' and 'what did the steps/collectives/retraces
    look like' (docs/OBSERVABILITY.md)."""
    import json as _json

    from . import telemetry

    return "\n\nTelemetry rollup:\n" + _json.dumps(telemetry.summary(),
                                                   sort_keys=True)


class scope:
    """Named annotation scope (reference: profiler.scope)."""

    def __init__(self, name="<unk>", append_mode=False):
        self._name = name
        self._ctx = None

    def __enter__(self):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(self._name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        return False


class Task:
    """Named task object (reference: profiler.Task)."""

    def __init__(self, domain=None, name="task"):
        self.name = name
        self._ctx = None

    def start(self):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def stop(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None


Frame = Task
Marker = Task

if env_bool("MXNET_PROFILER_AUTOSTART"):
    start()
