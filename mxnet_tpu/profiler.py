"""Profiler facade (reference: python/mxnet/profiler.py over src/profiler/ —
set_config/start/stop/dump, aggregate stats; SURVEY §5.1).

TPU-native: bridges to jax.profiler — start()/stop() capture a TensorBoard/
perfetto trace of XLA execution (the analog of the reference's Chrome
trace), and `scope`/`Task` map onto jax trace annotations.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from .base import MXNetError, env_bool

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "scope", "Task", "Frame", "Marker", "state"]

_config = {"filename": "profile.json", "profile_all": False,
           "trace_dir": None}
_running = False


def set_config(**kwargs):
    """Accepts the reference's kwargs (profile_all, profile_symbolic,
    profile_imperative, profile_memory, profile_api, filename, ...)."""
    _config.update(kwargs)
    if "filename" in kwargs:
        base = kwargs["filename"]
        _config["trace_dir"] = os.path.splitext(base)[0] + "_jax_trace"


def _trace_dir():
    if _config["trace_dir"] is None:
        _config["trace_dir"] = "mxnet_tpu_profile"
    return _config["trace_dir"]


def start():
    global _running
    import jax

    if _running:
        return
    jax.profiler.start_trace(_trace_dir())
    _running = True


def stop():
    global _running
    import jax

    if not _running:
        return
    jax.profiler.stop_trace()
    _running = False


def state():
    return "running" if _running else "stopped"


def pause():
    stop()


def resume():
    start()


def dump(finished=True, profile_process="worker"):
    """The jax trace is written on stop_trace; this flushes and reports."""
    if _running:
        stop()


def dumps(reset=False):
    return f"profile trace directory: {_trace_dir()}"


class scope:
    """Named annotation scope (reference: profiler.scope)."""

    def __init__(self, name="<unk>", append_mode=False):
        self._name = name
        self._ctx = None

    def __enter__(self):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(self._name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        return False


class Task:
    """Named task object (reference: profiler.Task)."""

    def __init__(self, domain=None, name="task"):
        self.name = name
        self._ctx = None

    def start(self):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def stop(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None


Frame = Task
Marker = Task

if env_bool("MXNET_PROFILER_AUTOSTART"):
    start()
