"""RecordIO file format.

Reference parity: python/mxnet/recordio.py (MXRecordIO, MXIndexedRecordIO,
IRHeader pack/unpack ~L1-400) and dmlc-core's recordio.h (magic 0xced7230a).

The on-disk format is byte-compatible with the reference so existing .rec
datasets read unchanged: [magic u32][lrecord u32][data][pad to 4B], where
lrecord encodes cflag in the upper 3 bits.  The high-throughput path is the
C++ pipeline (src/io); this module is the API-complete Python implementation.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_CFLAG_BITS = 29
_LREC_MASK = (1 << _CFLAG_BITS) - 1

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def _encode_lrec(cflag: int, length: int) -> int:
    return (cflag << _CFLAG_BITS) | length


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference ~L30)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.fid = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"Invalid flag {self.flag}")
        self.is_open = True

    def close(self):
        if self.is_open:
            self.fid.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            # interpreter teardown: the file object or its module may
            # already be finalized — nothing actionable at this point
            pass

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        del d["fid"]
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        is_open = d["is_open"]
        self.is_open = False
        self.fid = None
        if is_open:
            self.open()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        header = struct.pack("<II", _MAGIC, _encode_lrec(0, len(buf)))
        self.fid.write(header)
        self.fid.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.fid.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        header = self.fid.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError(f"{self.uri}: invalid RecordIO magic {magic:#x}")
        length = lrec & _LREC_MASK
        buf = self.fid.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fid.read(pad)
        return buf

    def tell(self):
        return self.fid.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with .idx sidecar (reference ~L150)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.fid.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a label header + payload (reference: recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        out = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        out = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
        out += label.tobytes()
    return out + s


def unpack(s: bytes):
    """Unpack a record into (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(s[: flag * 4], dtype=np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header: IRHeader, img, quality: int = 95, img_fmt: str = ".jpg"):
    """Encode an image array and pack (requires an encoder; see mxnet_tpu.image)."""
    from . import image

    encoded = image.imencode(img, img_fmt, quality)
    return pack(header, encoded)


def unpack_img(s: bytes, iscolor: int = -1):
    header, img_bytes = unpack(s)
    from . import image

    img = image.imdecode(img_bytes, 1 if iscolor != 0 else 0, to_ndarray=False)
    return header, img
