"""Checkpoint helpers (reference: python/mxnet/model.py save_checkpoint /
load_checkpoint ~L400 and BatchEndParam)."""
from __future__ import annotations

import json
from collections import namedtuple

from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

# `loss` (default None) may carry a LAZY loss handle (parallel.AsyncLoss
# or an unforced NDArray): callbacks must only force it at their display
# cadence (Speedometer does), never every batch — forcing is the host
# round-trip the async step pipeline exists to avoid.
BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals",
                            "loss"])
BatchEndParam.__new__.__defaults__ = (None,)


def save_checkpoint(prefix, epoch, symbol=None, arg_params=None,
                    aux_params=None, remove_amp_cast=True):
    """Write {prefix}-symbol.json + {prefix}-{epoch:04d}.params
    (reference format; arrays use the mxnet_tpu container)."""
    from . import ndarray as nd

    if symbol is not None:
        if hasattr(symbol, "save"):
            symbol.save(f"{prefix}-symbol.json")
        else:
            with open(f"{prefix}-symbol.json", "w") as f:
                json.dump({"format": "mxnet_tpu", "symbol": str(symbol)}, f)
    save_dict = {}
    for k, v in (arg_params or {}).items():
        save_dict[f"arg:{k}"] = v
    for k, v in (aux_params or {}).items():
        save_dict[f"aux:{k}"] = v
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    from . import ndarray as nd

    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Returns (symbol_or_None, arg_params, aux_params)."""
    symbol = None
    try:
        from . import symbol as sym_mod

        symbol = sym_mod.load(f"{prefix}-symbol.json")
    except Exception:
        symbol = None
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
