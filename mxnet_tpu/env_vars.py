"""Environment-variable compatibility map (reference: the ~80 documented
vars of docs/static_site/src/pages/api/faq/env_var.md, read via
dmlc::GetEnv at use-site; SURVEY §5.6).

Every load-bearing reference variable is listed with its disposition on
TPU so "is MXNET_X supported?" always has a definite answer:

  honored   — read by this tree at the cited site, same semantics;
  absorbed  — the responsibility moved into XLA/PjRt/jax; the variable is
              accepted but has nothing to configure (the jax-level control
              is named);
  n/a       — device-specific to CUDA/ROCm hardware, no TPU meaning.

`describe()` returns the table; `check(environ)` warns (once) about set
MXNET_* variables that are absorbed/n-a so silent expectation mismatches
surface in logs.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, Tuple

__all__ = ["ENV_VARS", "describe", "check"]

# name -> (disposition, detail)
ENV_VARS: Dict[str, Tuple[str, str]] = {
    "MXNET_ENGINE_TYPE": (
        "honored", "NaiveEngine -> synchronous dispatch with per-op "
        "block_until_ready (ops/registry.py via engine.is_naive)"),
    "MXNET_USE_FUSION": (
        "honored", "gates the Pallas fused kernels (ops/pallas enabled())"),
    "MXNET_SUBGRAPH_BACKEND": (
        "honored", "partitions symbol graphs at bind time (subgraph.py)"),
    "MXNET_PROFILER_AUTOSTART": (
        "honored", "starts the profiler at import (profiler.py)"),
    "MXNET_SAFE_ACCUMULATION": (
        "honored", "always-on behavior: fp16 matmul/conv upcast to f32, "
        "bf16 accumulates f32 natively on the MXU (ops/nn.py _safe_acc); "
        "setting it to 0 has no effect (accuracy is never degraded)"),
    "MXNET_TEST_DEVICE": (
        "honored", "test_utils.default_context device selection"),
    "MXNET_USE_NATIVE_IO": (
        "honored", "0 disables the libmxio C++ decode/augment pipeline and "
        "falls back to the python iterator (io/native.py)"),
    "MXNET_EXEC_BULK_EXEC_TRAIN": (
        "absorbed", "whole graphs compile into ONE XLA executable; there "
        "is no per-segment bulking to tune"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": (
        "absorbed", "same as MXNET_EXEC_BULK_EXEC_TRAIN"),
    "MXNET_GPU_MEM_POOL_TYPE": (
        "absorbed", "PjRt owns the device allocator; use "
        "XLA_PYTHON_CLIENT_MEM_FRACTION / _PREALLOCATE"),
    "MXNET_GPU_MEM_POOL_RESERVE": (
        "absorbed", "see MXNET_GPU_MEM_POOL_TYPE"),
    "MXNET_GPU_WORKER_NTHREADS": (
        "absorbed", "no per-device worker threads: XLA streams are "
        "scheduled by PjRt"),
    "MXNET_CPU_WORKER_NTHREADS": (
        "absorbed", "host parallelism: preprocess_threads on the data "
        "iterators; XLA CPU uses its own thread pool"),
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": (
        "n/a", "algorithm selection is the XLA compiler's job; no "
        "cuDNN/MIOpen find-mode on TPU"),
    "MXNET_KVSTORE_USETREE": (
        "absorbed", "collective topology is XLA's ICI routing"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (
        "absorbed", "no PS key sharding: gradients allreduce whole over "
        "DCN (parallel/dist.py)"),
    "MXNET_ENABLE_GPU_P2P": ("n/a", "ICI is always peer-to-peer"),
    "MXNET_ENGINE_INFO": (
        "absorbed", "dependency logging: use JAX_LOG_COMPILES / "
        "jax.profiler traces"),
    "OMP_NUM_THREADS": (
        "honored", "read by XLA:CPU's Eigen pool and OpenCV (libmxio)"),
    "DMLC_ROLE": ("honored", "launcher contract (tools/launch.py)"),
    "DMLC_PS_ROOT_URI": (
        "honored", "rendezvous address (parallel/dist.py init_from_env)"),
    "DMLC_PS_ROOT_PORT": ("honored", "see DMLC_PS_ROOT_URI"),
    "DMLC_NUM_WORKER": ("honored", "process count (parallel/dist.py)"),
    "DMLC_WORKER_ID": ("honored", "process rank (parallel/dist.py)"),
    "DMLC_NUM_SERVER": (
        "absorbed", "no parameter-server role in the SPMD design"),
    "PS_VERBOSE": ("absorbed", "see DMLC_NUM_SERVER"),
    # fault-tolerance layer (docs/FAULT_TOLERANCE.md) — TPU-native vars
    # with no reference counterpart
    "MX_FAULT_SPEC": (
        "honored", "fault-injection harness: crash / crash-write / "
        "torn-write / slow-write specs with rank=/shard=/if-restart= "
        "qualifiers (fault.py, hooks in checkpoint.py; torn-write:shard=R "
        "corrupts one rank's shard file of a sharded checkpoint)"),
    "MX_CKPT_SHARDED": (
        "honored", "default AsyncCheckpointer(sharded=) — shard-granular "
        "(format 2) checkpoints: every rank writes only its own shards, "
        "zero collectives on the save path (checkpoint.py, "
        "docs/FAULT_TOLERANCE.md §Shard-granular checkpoints)"),
    "MX_CKPT_SHARD_WAIT_S": (
        "honored", "seconds the leader rank waits for peer shard commit "
        "markers before publishing a sharded checkpoint step (default 60; "
        "the preemption save_now path caps it at 2s) (checkpoint.py)"),
    "MX_RENDEZVOUS_TIMEOUT": (
        "honored", "seconds a (re)started rank retries "
        "jax.distributed.initialize with backoff (parallel/dist.py)"),
    "MX_RESTART_COUNT": (
        "honored", "gang incarnation index exported by tools/launch.py "
        "--max-restarts; read by fault.py if-restart= and resume logic"),
    "MX_ELASTIC": (
        "honored", "exported (=1) to workers by tools/launch.py --elastic "
        "so they know the supervisor may re-rendezvous them at a "
        "different world size (docs/FAULT_TOLERANCE.md §Elastic resize)"),
    "MX_PREV_NUM_PROCS": (
        "honored", "previous world size, exported by the --elastic "
        "supervisor on the FIRST incarnation after a gang resize; "
        "parallel/dist.py records the telemetry `resize` event off it "
        "(the segment marker trace_report/mem_report key on) and worker "
        "resume logic knows the restored checkpoint needs resharding"),
    # launcher contract (tools/launch.py exports; parallel/dist.py reads) —
    # TPU-native spellings of the DMLC_* variables above
    "MX_COORDINATOR": (
        "honored", "host:port of the jax.distributed coordination service "
        "(parallel/dist.py init_from_env)"),
    "MX_NUM_PROCS": (
        "honored", "gang process count (parallel/dist.py init_from_env)"),
    "MX_PROC_ID": (
        "honored", "this process's gang rank (parallel/dist.py, fault.py "
        "rank= qualifier, telemetry.py stream naming)"),
    "MX_FORCE_CPU": (
        "honored", "pin workers to the CPU jax backend (tools/launch.py "
        "--force-cpu exports it; parallel/dist.py honors it)"),
    # fused optimizer apply + bucketed allreduce (docs/PERFORMANCE.md)
    "MX_FUSED_UPDATE": (
        "honored", "0 disables the fused optimizer apply (one jitted "
        "update call for all dense params) and pins the per-param "
        "Updater path (optimizer/fused.py get_updater)"),
    "MX_ALLREDUCE_BUCKET_MB": (
        "honored", "gradient-allreduce bucket cap in MB (default 32): "
        "per-param pushpulls coalesce into flat buckets this large so "
        "one collective moves many grads; 0 disables bucketing "
        "(parallel/dist.py bucket_cap_bytes, kvstore.py push_bucketed)"),
    # async step pipeline (docs/PERFORMANCE.md §Async pipeline)
    "MX_ASYNC_INFLIGHT": (
        "honored", "bounded in-flight dispatch window: how many "
        "dispatched-but-unforced steps may be pending before dispatch "
        "blocks on the oldest (default 2; 0 = synchronous, every step "
        "forced at dispatch).  Read per step call by "
        "parallel/async_loss.py; honored by DataParallelStep.step (lazy "
        "AsyncLoss), gluon Trainer.step and module.Module.update (step "
        "fences)"),
    # superstep compiled training + AOT executable cache
    # (docs/PERFORMANCE.md §Superstep & AOT executable cache)
    "MX_SUPERSTEP": (
        "honored", "transparent superstep group size K: every K "
        "DataParallelStep.step() calls dispatch as ONE compiled "
        "lax.scan over the step program (per-step lr/RNG become scanned "
        "arrays; losses return as lazy per-step views).  0/unset = off; "
        "defaults off on CPU meshes regardless of K — XLA:CPU runs scan "
        "bodies ~4.7x slower (parallel/data_parallel.py superstep_k)"),
    "MX_SUPERSTEP_FORCE_CPU": (
        "honored", "1 overrides the CPU-mesh gate of MX_SUPERSTEP (the "
        "CPU parity-test/bench override; production CPU meshes should "
        "leave it off — see the MX_SUPERSTEP caveat)"),
    "MX_EXECUTABLE_CACHE_DIR": (
        "honored", "directory of the persistent AOT executable cache: "
        "DataParallelStep/FusedUpdater jit sites lower ahead-of-time and "
        "serialize the compiled program here, keyed by "
        "(memwatch.fingerprint, jax version, platform, mesh shape); a "
        "restarted process deserializes instead of recompiling "
        "(aot_cache.py).  Unset = no persistence"),
    "MX_EXECUTABLE_CACHE": (
        "honored", "0 kills all AOT executable persistence even when "
        "MX_EXECUTABLE_CACHE_DIR is set — no loads, no stores, plain "
        "jit dispatch (aot_cache.enabled)"),
    # inference serving: continuous batching + paged KV cache
    # (docs/SERVING.md)
    "MX_SERVE_SLOTS": (
        "honored", "fixed decode-slot count of the serving engine — the "
        "in-flight batch width of the ONE compiled decode step (default "
        "8; serving/engine.py ServingEngine)"),
    "MX_SERVE_PAGE_SIZE": (
        "honored", "tokens per KV-cache page (default 16): the paged "
        "pool granularity requests allocate/free in "
        "(serving/paged_cache.py)"),
    "MX_SERVE_POOL_PAGES": (
        "honored", "total pages in the per-layer KV pools (default 0 = "
        "auto: slots * ceil(max_len/page_size) + 1, every slot can reach "
        "max_len); the engine raises when active requests exhaust it "
        "(serving/engine.py _ensure_pages)"),
    "MX_SERVE_QUEUE": (
        "honored", "request-queue bound (default 256; 0 = unbounded): a "
        "full queue rejects submits loudly — the serving backpressure "
        "surface (serving/scheduler.py)"),
    "MX_SERVE_STREAM_EVERY": (
        "honored", "decode steps per stream boundary (default 4): token "
        "readback, EOS eviction and mid-flight admission happen at this "
        "cadence — the host never blocks per token "
        "(serving/engine.py)"),
    "MX_SERVE_FLASH": (
        "honored", "paged-attention path: 'auto' (default) fuses through "
        "the Pallas ragged paged kernel only where it compiles natively "
        "(TPU), 1 forces it (interpret-mode tests), 0 pins the XLA "
        "gather path — the bitwise-parity path "
        "(serving/engine.py _serve_fused)"),
    # serving front door (docs/SERVING.md §Front door / §Sampling /
    # §Prefix cache / §Speculative decoding) — everything defaults OFF
    # or to the greedy parity pin
    "MX_SERVE_SAMPLING": (
        "honored", "1 builds the engine with per-slot sampling state "
        "(temperature/top-k/top-p/RNG as device decode state; default 0 "
        "= greedy-only, trace and AOT fingerprint unchanged); a "
        "temperature-0 request on a sampling engine is still BITWISE "
        "greedy (serving/engine.py)"),
    "MX_SERVE_SPEC_K": (
        "honored", "speculative decoding draft depth (default 0 = off): "
        "a host-side draft proposes up to K tokens and ONE compiled "
        "(\"verify\", K) dispatch checks them all — greedy output stays "
        "bitwise identical, sampling stays distribution-identical "
        "(serving/engine.py, serving/speculative.py)"),
    "MX_SERVE_PREFIX_CACHE": (
        "honored", "1 enables the copy-on-write prefix cache (default "
        "0): identical (source, forced-prefix) requests fork refcounted "
        "KV pages + reuse prefill rows instead of recomputing; entries "
        "are weight-generation-stamped and drop at a hot-swap flip "
        "(serving/engine.py, serving/scheduler.py PrefixCache)"),
    "MX_SERVE_PREFIX_ENTRIES": (
        "honored", "prefix-cache LRU bound (default 64 entries); under "
        "pool pressure entries also evict before any live request is "
        "preempted (serving/engine.py _ensure_pages)"),
    "MX_SERVE_PREFIX_CHUNK": (
        "honored", "tokens per (\"ingest\", K) teacher-forcing dispatch "
        "when a prefix misses the cache (default 8): one executable "
        "reused for any prefix length (serving/engine.py "
        "_ingest_prefix)"),
    "MX_SERVE_PORT": (
        "honored", "replica HTTP port: N binds N+rank (0/unset = "
        "ephemeral); the bound port is advertised via "
        "serve-port-<rank>.json under MX_TELEMETRY_DIR for router "
        "discovery (serving/router.py ReplicaServer)"),
    "MX_SERVE_ROUTER_PORT": (
        "honored", "router bind port (0/unset = ephemeral) for the "
        "multi-replica front door (serving/router.py Router)"),
    "MX_SERVE_HOST": (
        "honored", "bind host for replica servers and the router "
        "(default 127.0.0.1; 0.0.0.0 exposes them cross-host) "
        "(serving/router.py)"),
    "MX_SERVE_HEALTH_SEC": (
        "honored", "router health-poll cadence in seconds (default 2.0): "
        "each tick re-discovers portfiles and probes every replica's "
        "/healthz — dead replicas leave rotation, recovered/undrained "
        "ones rejoin (serving/router.py Router)"),
    "MX_SERVE_TEMPERATURE": (
        "honored", "fleet-wide default sampling temperature applied at "
        "the HTTP layer when a /generate body omits it (default 0 = "
        "greedy; never consulted inside the engine) "
        "(serving/router.py)"),
    "MX_SERVE_TOP_K": (
        "honored", "fleet-wide default top-k for /generate bodies that "
        "omit it (default 0 = off) (serving/router.py)"),
    "MX_SERVE_TOP_P": (
        "honored", "fleet-wide default nucleus top-p for /generate "
        "bodies that omit it (default 1.0 = off) (serving/router.py)"),
    # serving SLO counters (docs/SERVING.md §SLO telemetry; visible live
    # via the metrics endpoint and in the launch.py gang merge)
    "MX_SERVE_SLO_TTFT_MS": (
        "honored", "submission->first-token SLO in ms (queue wait "
        "INCLUDED — the user-visible TTFT; 0/unset = no SLO): a "
        "completed request whose TTFT exceeds it bumps "
        "mx_serve_slo_violations_total{stage=\"ttft\"} and records a "
        "serve_slo_violation event (telemetry.record_serve_request)"),
    "MX_SERVE_SLO_TPOT_MS": (
        "honored", "time-per-output-token SLO in ms (decode wall / "
        "tokens; 0/unset = no SLO): violations bump "
        "mx_serve_slo_violations_total{stage=\"tpot\"} "
        "(telemetry.record_serve_request)"),
    # fleet-wide request tracing (docs/OBSERVABILITY.md §Request tracing)
    "MX_RQTRACE": (
        "honored", "0/false/off disables serving request tracing end to "
        "end — no trace minting, no X-MX-Trace header, no /tracez "
        "bookkeeping (serving/router.py rqtrace_enabled; default on; "
        "the bench lever for the rqtrace_overhead <2% gate)"),
    "MX_RQTRACE_SAMPLE": (
        "honored", "head-based sampling rate in [0,1] for request "
        "traces (default 1.0): unsampled requests skip span emission "
        "on the hot path but are measured anyway — an error or TTFT "
        "SLO breach records their spans retroactively (late_sampled), "
        "so the tail is never lost (serving/router.py mint_trace)"),
    "MX_RQTRACE_TRACEZ_K": (
        "honored", "how many completed request trees the /tracez rings "
        "keep — the Router's fleet-level ring and each rank's "
        "telemetry.recent_requests ring (default 32) "
        "(serving/router.py + telemetry.py)"),
    "MX_RQTRACE_STRAGGLER_X": (
        "honored", "tools/serve_report.py labels a replica a straggler "
        "(and attributes its cause-less slow requests to it) when its "
        "mean decode ms/token exceeds this multiple of the fleet "
        "median (default 2.0)"),
    # live metrics endpoint (docs/OBSERVABILITY.md §Live metrics)
    "MX_METRICS_PORT": (
        "honored", "per-rank HTTP /metrics /healthz /statusz endpoint "
        "(metrics_server.py): unset/off = disabled (default); 0/auto = "
        "ephemeral port advertised via metrics-port-<R>.json next to "
        "the heartbeat (tools/launch.py --metrics-port discovers it for "
        "the merged gang /metrics); N>0 = bind N+rank"),
    "MX_METRICS_HOST": (
        "honored", "bind address of the live metrics endpoint (default "
        "127.0.0.1; set 0.0.0.0 to expose it to a cross-host scraper) "
        "(metrics_server.py)"),
    # runtime telemetry (docs/OBSERVABILITY.md)
    "MX_TELEMETRY_DIR": (
        "honored", "enables the telemetry recorder: one rank-<R>.jsonl "
        "event stream + heartbeat-<R>.json per rank under this directory "
        "(telemetry.py; polled by tools/launch.py)"),
    "MX_TELEMETRY_FLUSH_SEC": (
        "honored", "seconds between background flushes of buffered "
        "telemetry events to the JSONL sink (telemetry.py; default 1.0)"),
    "MX_HEARTBEAT_SEC": (
        "honored", "min seconds between heartbeat-file writes; the "
        "launch.py supervisor flags a rank stale after 5x this "
        "(telemetry.py + tools/launch.py; default 5.0)"),
    "MX_TELEMETRY_RETRACE_LIMIT": (
        "honored", "distinct jit signatures one executor may accumulate "
        "before the retrace-storm warning fires (telemetry.py; default 5)"),
    # gang-wide trace analysis (docs/OBSERVABILITY.md §Tracing & analysis)
    "MX_TELEMETRY_SPANS": (
        "honored", "0 disables span tracing (the nested "
        "span_begin/span_end events threaded through "
        "DataParallelStep.step, kvstore.push_bucketed, FusedUpdater, "
        "checkpoints, and the async ring) while keeping step events and "
        "heartbeats; default on whenever the recorder is on "
        "(telemetry.py spans_enabled)"),
    "MX_TRACE_EXPORT": (
        "honored", "default off; 1/true exports a merged Chrome/Perfetto "
        "trace.json (rank 0) plus per-rank OpenMetrics metrics-<R>.prom "
        "snapshots into MX_TELEMETRY_DIR at process exit, any other "
        "value names the target directory (telemetry.py "
        "_trace_export_target)"),
    "MX_TRACE_WINDOW": (
        "honored", "sliding window of newest steady steps tools/"
        "trace_report.py uses for the per-rank skew table (default 20)"),
    "MX_TRACE_STRAGGLER_PCT": (
        "honored", "trace_report.py flags a rank slower (step-wall rule) "
        "or idler (idle-gap rule) than the best rank by more than this "
        "percent (default 25)"),
    "MX_TRACE_HEARTBEAT_GAP_SEC": (
        "honored", "trace_report.py flags stretches where a rank's event "
        "stream went silent longer than this many seconds (default 30)"),
    # unified parallelism Plan + analytic auto-sharding planner
    # (docs/PERFORMANCE.md §Plan & planner)
    "MX_PLAN": (
        "honored", "parallelism-layout override for the analytic "
        "planner: 'auto' (default) picks the argmin of the cost model "
        "over every legal dp*tp*pp*sp factorization; 'dp'/'tp'/'pp'/"
        "'sp' pin the corresponding axis family; 'ring'/'ulysses' "
        "additionally select the SP attention mechanism "
        "(parallel/planner.py plan_for)"),
    # precision subsystem: graph-level AMP, traced loss scaling, int8
    # serving (docs/PRECISION.md)
    "MX_AMP": (
        "honored", "enables the graph-level AMP cast pass for compiled "
        "steps built without an explicit Plan.precision: bf16/bfloat16/1 "
        "or fp16/float16 (fp16 defaults dynamic loss scaling on); read "
        "ONCE at step construction and recorded on the Plan "
        "(precision/config.py PrecisionConfig.from_env)"),
    "MX_AMP_POLICY": (
        "honored", "inline-JSON override of the AMP op-class lists: "
        '{"low": [...], "widen": [...], "dtype": ...} — low-class ops '
        "compute in the AMP dtype, widen-class ops force f32 "
        "(precision/config.py AmpPolicy)"),
    "MX_LOSS_SCALE": (
        "honored", "traced dynamic loss scaling config under MX_AMP: "
        "'dynamic' (or 1), a fixed scale float (static), or 0/off; "
        "unset = on for fp16, off for bf16.  All scale/overflow/skip "
        "transitions run inside the compiled step as device values "
        "(precision/loss_scale.py)"),
    "MX_QUANTIZE": (
        "honored", "int8 (or 1) routes maybe_quantize_adapter to build a "
        "calibrated int8 serving adapter — Dense/Conv in the traced "
        "decode/prefill graphs lower onto the ops/quantization.py int8 "
        "primitives; the quant config joins the AOT-cache fingerprint "
        "so a restart under different settings misses "
        "(precision/quantize.py)"),
    "MX_QUANT_CALIB": (
        "honored", "calibration mode for MX_QUANTIZE: naive (per-layer "
        "min/max, default) or entropy (KL-optimal threshold over a "
        "streaming histogram) (precision/quantize.py; calibrators from "
        "contrib/quantization.py)"),
    "MX_SERVE_INT4": (
        "honored", "int4 (or 1) routes maybe_int4_adapter to build a "
        "weight-only int4 serving adapter: Dense/Conv weights packed 2 "
        "per byte with group-wise f16 scales, dequantized in-trace "
        "inside the engine's compiled decode/prefill bodies — ~0.14x "
        "weight bytes, no calibration; rejected if MX_QUANTIZE is also "
        "set (precision/quantize.py)"),
    "MX_QUANT_GROUP": (
        "honored", "group size for MX_SERVE_INT4's group-wise int4 "
        "scales (default 32, must be even): one f16 scale per group of "
        "weights along the input dim — smaller groups trade bytes for "
        "accuracy (contrib/quantization._quantize_weight_int4_np)"),
    # pass pipeline (docs/PRECISION.md §Pass pipeline; passes/)
    "MX_PASSES": (
        "honored", "comma-separated per-pass toggles applied to every "
        "constructed pass pipeline: 'name' asserts the pass type is "
        "registered, '-name' disables that pass where present (the "
        "disabled pass contributes nothing to the trace or the pipeline "
        "fingerprint — bitwise the pass-less program); unknown names "
        "raise listing the registered set (passes/pipeline.py "
        "apply_env_toggles)"),
    "MX_PALLAS_FUSED": (
        "honored", "fused-kernel substitution pass (ops/pallas/"
        "registry.py): auto (default) substitutes registered Pallas "
        "kernels for their op-class only where they compile natively "
        "(TPU, MXNET_USE_FUSION on); 1 forces the pass (interpret-mode "
        "kernels — the CPU test path); 0 pins the stock op "
        "implementations (passes/builtin.fused_kernels_from_env)"),
    # memory & compile observability (docs/OBSERVABILITY.md §Memory)
    "MX_MEMWATCH": (
        "honored", "device-memory watchdog riding the telemetry "
        "recorder (memwatch.py): on by default whenever MX_TELEMETRY_DIR "
        "is set; 0 disables the whole subsystem — sampling, compile "
        "accounting (incl. the analysis retrace), and OOM post-mortems; "
        "'full' additionally captures compiled memory_analysis() "
        "temp/arg/output bytes per executable at the cost of one "
        "duplicate XLA compile each"),
    "MX_MEMWATCH_EVERY": (
        "honored", "memory-sample cadence: one live-array census + "
        "device memory_stats snapshot every N step-boundary "
        "observations (default 10; memwatch.on_step — checkpoint "
        "save/load always samples)"),
    "MX_MEMWATCH_LEAK_WINDOW": (
        "honored", "sliding-window length of the monotonic-growth leak "
        "detector (default 12 samples; memwatch.py sample(), also the "
        "default verdict window of tools/mem_report.py)"),
}

_warned = False


def describe() -> str:
    width = max(len(k) for k in ENV_VARS) + 2
    lines = [f"{'Variable':<{width}}{'Disposition':<12}Detail"]
    for name, (disp, detail) in sorted(ENV_VARS.items()):
        lines.append(f"{name:<{width}}{disp:<12}{detail}")
    return "\n".join(lines)


def check(environ=None) -> None:
    """Log (once) any set MXNET_* variable that has no effect here."""
    global _warned
    if _warned:
        return
    _warned = True
    environ = environ if environ is not None else os.environ
    for name, value in environ.items():
        if not name.startswith("MXNET_"):
            continue
        disp, detail = ENV_VARS.get(name, (None, None))
        if disp in ("absorbed", "n/a"):
            logging.getLogger("mxnet_tpu").info(
                "env var %s=%s has no effect on TPU (%s): %s",
                name, value, disp, detail)
        elif disp is None:
            logging.getLogger("mxnet_tpu").info(
                "env var %s is not recognized by mxnet_tpu (see "
                "mxnet_tpu.env_vars.describe())", name)
