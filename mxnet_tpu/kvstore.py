"""KVStore: the data-parallel aggregation facade.

Reference parity: python/mxnet/kvstore.py over src/kvstore/ —
KVStoreLocal (kvstore_local.h ~L200), CommDevice reduce (comm.h ~L500),
KVStoreNCCL (kvstore_nccl.h), KVStoreDist (kvstore_dist.h).

TPU-native mapping (SURVEY §2.3/§5.8):
  * 'local' / 'device' / 'nccl'  -> single-process aggregation across the
    local device list.  The hand-rolled tree reduce / RCCL rings of the
    reference are unnecessary: the fused pjit training-step path
    (mxnet_tpu.parallel) emits XLA ICI collectives; this eager facade sums
    on the lead device, preserving exact KVStore push/pull semantics.
  * 'dist_sync' / 'dist_sync_device' -> same API over a multi-host program
    (jax.distributed); gradients are globally reduced; servers do not exist
    as processes — the "server-side optimizer" (update_on_kvstore) runs
    identically on every host, which is numerically equivalent to the
    reference's sync PS protocol.
  * 'dist_async' -> unsupported by design: async parameter serving has no
    SPMD analog (documented divergence).
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Union

from . import telemetry
from .base import MXNetError

__all__ = ["KVStore", "create"]


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class KVStore:
    """Key-value store for parameter synchronization."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._store: Dict[Any, Any] = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._psum_cache: Dict[Any, Any] = {}
        self._psum_seen: set = set()
        # per-(devices, shape, dtype) persistent AOT executables of the
        # collective reduce (MX_EXECUTABLE_CACHE_DIR): a gang restart
        # deserializes instead of re-tracing; False = resolution failed,
        # stay on the plain jit path (docs/PERFORMANCE.md §AOT cache)
        self._psum_aot: Dict[Any, Any] = {}
        if kv_type.startswith("dist"):
            # rendezvous with the coordination service when launched by
            # tools/launch.py (reference: ps::Postoffice::Start on first
            # KVStoreDist construction)
            from .parallel import dist

            dist.init_from_env()

    # ------------------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        if self._type.startswith("dist"):
            from .parallel import dist

            return dist.process_index()
        return 0

    @property
    def num_workers(self) -> int:
        if self._type.startswith("dist"):
            from .parallel import dist

            return dist.process_count()
        return 1

    # ------------------------------------------------------------------
    def init(self, key, value) -> None:
        keys, values = self._key_value(key, value)
        dist_bcast = self._type.startswith("dist") and self.num_workers > 1
        for k, v in zip(keys, values):
            vals = _as_list(v)
            init_val = vals[0].copy()
            if dist_bcast:
                # reference contract (KVStoreDist): only rank 0's init value
                # reaches the store; every worker starts from the SAME
                # parameters.  Broadcast = allreduce of (rank0 ? v : 0).
                if self.rank != 0:
                    init_val = init_val * 0
                init_val = self._global_sum(init_val)
            self._store[k] = init_val

    def push(self, key, value, priority: int = 0) -> None:
        keys, values = self._key_value(key, value)
        for k, v in zip(keys, values):
            merged = self._reduce(_as_list(v))
            if self._type.startswith("dist") and self.num_workers > 1:
                merged = self._global_sum(merged)
            self._store_merged([(k, merged)])

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True) -> None:
        keys, outs = self._key_value(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            for dst in _as_list(o):
                dst._set_data(self._to_ctx(src, dst.context))

    def pushpull(self, key, value, out=None, priority: int = 0) -> None:
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    # ------------------------------------------------------------------
    # bucketed gradient aggregation (docs/PERFORMANCE.md)
    # ------------------------------------------------------------------
    def push_bucketed(self, key, value, priority: int = 0) -> int:
        """Push many keys at once, coalescing their values into size-capped
        flat buckets (MX_ALLREDUCE_BUCKET_MB, default 32) so ONE collective
        moves many gradients instead of one per key.  Store contents after
        the call are exactly what per-key ``push`` would have produced
        (unflatten restores every key before it reaches the store or the
        updater), so ``pull`` semantics are unchanged.

        Returns the number of flat buckets reduced; 0 means everything fell
        back to per-key pushes (bucketing disabled, or sparse/ragged
        values).  When the installed updater is a ``FusedUpdater`` the
        server-side optimizer also applies in one jitted call for the whole
        batch rather than once per key.
        """
        from .parallel.dist import bucket_cap_bytes

        keys, values = self._key_value(key, value)
        cap = bucket_cap_bytes()
        if cap <= 0:
            for k, v in zip(keys, values):
                self.push(k, v, priority)
            return 0
        from .ndarray.sparse import BaseSparseNDArray

        with telemetry.span("push_bucketed", n_keys=len(keys)):
            groups: Dict[Any, List] = {}  # (ctx tuple, dtype) -> [(k, vals)]
            fallback: List = []
            for k, v in zip(keys, values):
                vals = _as_list(v)
                lead = vals[0]
                if (any(isinstance(x, BaseSparseNDArray) for x in vals)
                        or any(x._data.dtype != lead._data.dtype
                               or x.shape != lead.shape for x in vals[1:])):
                    fallback.append((k, vals))
                    continue
                gkey = (tuple(x.context for x in vals), str(lead._data.dtype))
                groups.setdefault(gkey, []).append((k, vals))
            n_buckets = 0
            merged_kv: List = []  # (k, merged NDArray) in caller key order
            for (_ctxs, _dt), items in groups.items():
                bucket: List = []
                nbytes = 0
                for k, vals in items:
                    sz = int(vals[0].size) * vals[0]._data.dtype.itemsize
                    if bucket and nbytes + sz > cap:
                        merged_kv.extend(self._reduce_bucket(bucket))
                        n_buckets += 1
                        bucket, nbytes = [], 0
                    bucket.append((k, vals))
                    nbytes += sz
                if bucket:
                    merged_kv.extend(self._reduce_bucket(bucket))
                    n_buckets += 1
            self._store_merged(merged_kv)
            for k, vals in fallback:
                self.push(k, vals, priority)
            return n_buckets

    def _reduce_bucket(self, bucket) -> List:
        """Reduce one flat bucket across devices (and hosts for dist_*);
        returns the per-key merged values, unflattened."""
        from .ndarray import NDArray
        from .parallel.dist import flatten_bucket, unflatten_bucket

        shapes = [tuple(vals[0].shape) for _k, vals in bucket]
        if len(bucket) == 1:
            # a bucket of one key gains nothing from the flatten round-trip
            k, vals = bucket[0]
            with telemetry.span("bucket_collective", paired=True, n_keys=1):
                merged = self._reduce(vals)
                if self._type.startswith("dist") and self.num_workers > 1:
                    merged = self._global_sum(merged)
            return [(k, merged)]
        ndev = len(bucket[0][1])
        with telemetry.span("bucket_flatten", n_keys=len(bucket)):
            flats = []
            for d in range(ndev):
                flat = flatten_bucket([vals[d]._data for _k, vals in bucket])
                flats.append(NDArray(flat, ctx=bucket[0][1][d].context))
        with telemetry.span("bucket_collective", paired=True,
                            n_keys=len(bucket)):
            merged = self._reduce(flats)
            if self._type.startswith("dist") and self.num_workers > 1:
                merged = self._global_sum(merged)
        with telemetry.span("bucket_unflatten", n_keys=len(bucket)):
            segments = unflatten_bucket(merged._data, shapes)
            out = [(k, NDArray(seg, ctx=merged.context))
                   for (k, _vals), seg in zip(bucket, segments)]
        return out

    def _store_merged(self, merged_kv) -> None:
        """The tail of ``push`` for already-reduced values: store them, or
        hand them to the server-side optimizer — batched through the fused
        updater when several keys arrive at once (the bucketed path)."""
        if self._updater is None:
            for k, merged in merged_kv:
                self._store[k] = merged
            return
        entries = []
        for k, merged in merged_kv:
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            # the updater computes eagerly on one device — localize BOTH
            # operands (a mesh-replicated merge from a collective reduce,
            # and a store value left replicated by an earlier non-updater
            # push) so eager ops don't mix device sets
            ctx = self._store[k].context
            merged = self._localize(merged, ctx)
            self._store[k] = self._localize(self._store[k], ctx)
            entries.append((self._updater_key(k), merged, self._store[k]))
        apply_batch = None
        if len(entries) > 1:
            from .optimizer.fused import FusedUpdater

            # scope the batched fast path to the type that defines it — a
            # user updater installed via set_updater may coincidentally
            # have an `apply` with a different contract
            if isinstance(self._updater, FusedUpdater):
                apply_batch = self._updater.apply
        if apply_batch is not None:
            # donate=False: pulled store values alias into caller arrays
            apply_batch(entries)
        else:
            for uk, merged, stored in entries:
                self._updater(uk, merged, stored)

    def broadcast(self, key, value, out, priority: int = 0) -> None:
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None) -> None:
        # sparse storage is emulated densely (SURVEY §7.3 item 8)
        self.pull(key, out, priority)

    # ------------------------------------------------------------------
    def set_updater(self, updater) -> None:
        self._updater = updater

    def set_optimizer(self, optimizer) -> None:
        """Install a server-side optimizer (reference: _send_command_to_servers
        pickles it; here the 'server' is this process — and every host in the
        dist_sync case, which the sync protocol makes equivalent)."""
        from . import optimizer as opt_mod

        # round-trip through pickle to mirror the reference's serialization
        # boundary (catches unpicklable user optimizers early)
        optimizer = pickle.loads(pickle.dumps(optimizer))
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_gradient_compression(self, compression_params: Dict) -> None:
        # DCN/ICI collectives don't need 2-bit compression; accepted for API
        # compatibility (reference: gradient_compression.cc)
        self._compression_params = compression_params

    # ------------------------------------------------------------------
    def barrier(self) -> None:
        if self._type.startswith("dist"):
            from .parallel import host_barrier

            host_barrier()

    def save_optimizer_states(self, fname: str, dump_optimizer: bool = False) -> None:
        if self._updater is None:
            raise MXNetError("no updater installed")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no updater installed")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # ------------------------------------------------------------------
    def _key_value(self, key, value):
        if isinstance(key, (list, tuple)):
            if value is None:
                return list(key), [None] * len(key)
            return list(key), list(value)
        return [key], [value]

    @staticmethod
    def _updater_key(k):
        return int(k) if isinstance(k, str) and k.isdigit() else k

    def _reduce(self, vals: List):
        """Sum a per-device gradient list (CommDevice::Reduce).

        When the values live on DISTINCT devices, the sum runs as a
        compiled all-reduce (shard_map psum over a one-axis mesh of those
        devices) and the result is left replicated across them — on TPU
        the traffic rides ICI and a subsequent pull() to any contributing
        device is a local-shard fetch, not a broadcast.  This removes the
        r3-flagged lead-device funnel (all grads staged through one HBM).
        Single-device / duplicated-device lists keep the simple
        sum-on-lead path.

        Sparse values densify first: per-worker nnz/rows differ, so the
        collective needs the full logical shape (the reference's dist
        row_sparse key encoding is a documented non-goal; dense aggregation
        is correct, just not compact)."""
        from .ndarray.sparse import BaseSparseNDArray

        vals = [v.todense() if isinstance(v, BaseSparseNDArray) else v
                for v in vals]
        if len(vals) == 1:
            return vals[0].copy()
        lead = vals[0].context
        import jax

        devices = [v.context.jax_device for v in vals]
        if len(set(devices)) == len(vals):
            return self._reduce_collective(vals, devices)
        total = vals[0]._data
        for v in vals[1:]:
            arr = v._data
            if v.context != lead:
                arr = jax.device_put(arr, lead.jax_device)
            total = total + arr
        from .ndarray import NDArray

        return NDArray(total, ctx=lead)

    def _reduce_collective(self, vals: List, devices: List):
        """All-reduce across distinct devices; result replicated on all."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from .ndarray import NDArray

        shape = tuple(vals[0].shape)
        key = (tuple(devices), len(shape))
        entry = self._psum_cache.get(key)
        cold = entry is None
        if entry is None:
            from .parallel.sharding import shard_map_compat

            mesh = Mesh(np.array(devices), ("kv",))
            fn = jax.jit(shard_map_compat(
                lambda x: jax.lax.psum(x, "kv")[0],
                mesh=mesh, in_specs=P("kv"),
                out_specs=P(*([None] * len(shape)))))
            entry = self._psum_cache[key] = (mesh, fn)
        mesh, fn = entry
        # pin FIRST, then expand: uncommitted arrays (made under
        # jax.default_device) would otherwise bounce through the default
        # device during the expand_dims dispatch — re-creating the funnel
        parts = [jnp.expand_dims(jax.device_put(v._data, d), 0)
                 for v, d in zip(vals, devices)]
        stacked = jax.make_array_from_single_device_arrays(
            (len(vals),) + shape, NamedSharding(mesh, P("kv")), parts)
        import time as _time

        from . import aot_cache, telemetry

        run = fn
        aot_info = {}
        if aot_cache.enabled():
            # persistent AOT executable per (devices, shape, dtype) —
            # the PR 9 recipe at the reduce site: a restarted gang
            # deserializes the psum program instead of re-tracing it
            aot_key = (key, shape, str(vals[0]._data.dtype))
            aot = self._psum_aot.get(aot_key)
            if aot is None:
                from . import memwatch

                exec_, aot_info = aot_cache.get_or_compile(
                    fn, (stacked,),
                    fingerprint=memwatch.fingerprint(
                        ("reduce", len(devices), shape,
                         str(vals[0]._data.dtype))),
                    platform=devices[0].platform,
                    mesh_shape=(("kv", len(devices)),),
                    device_ids=tuple(int(d.id) for d in devices))
                self._psum_aot[aot_key] = (exec_ if exec_ is not None
                                           else False)
                aot = exec_
            if aot is not None and aot is not False:
                run = aot

        t0 = _time.perf_counter()
        reduced = run(stacked)  # replicated over the kv mesh
        if telemetry.enabled():
            # cold = this (devices, ndim) program was jit-built above;
            # jax also re-specializes per concrete shape — approximate
            # that with a per-shape first-use check so compile time never
            # pollutes the comm aggregates
            shape_key = (key, shape, str(vals[0]._data.dtype))
            traced = cold or shape_key not in self._psum_seen
            self._psum_seen.add(shape_key)
            telemetry.record_collective(
                "device_allreduce",
                nbytes=int(np.prod(shape)) * vals[0]._data.dtype.itemsize,
                wall_s=_time.perf_counter() - t0, ndev=len(vals),
                traced=traced)
            if traced:
                # one compile event per specialized psum executable — the
                # cache-entry schema the AOT executable cache will key on
                from . import memwatch

                memwatch.note_compile(
                    "KVStore.device_allreduce",
                    ("kvstore_psum", len(devices), shape,
                     str(vals[0]._data.dtype)),
                    wall_s=_time.perf_counter() - t0, site="kvstore",
                    # a deserialized executable never traced the psum
                    # fn — don't pay that trace just for cost analysis
                    jitted=(None if aot_info.get("cache_hit") else fn),
                    args=(memwatch.shape_structs(stacked),),
                    ndev=len(devices),
                    **{k: v for k, v in aot_info.items() if k != "meta"})
        return NDArray(reduced, ctx=vals[0].context)

    def _global_sum(self, nd):
        from .parallel import global_allreduce

        return global_allreduce(nd)

    def _localize(self, nd, ctx):
        """A single-device NDArray on ctx, fetching the local shard when
        the value is mesh-replicated (collective _reduce output)."""
        from .ndarray import NDArray

        return NDArray(self._to_ctx(nd, ctx), ctx=ctx)

    def _to_ctx(self, nd, ctx):
        import jax

        arr = nd._data
        multi = len(getattr(arr, "sharding", None).device_set) > 1 \
            if hasattr(arr, "sharding") else False
        if nd.context == ctx and not multi:
            return arr
        # replicated-over-mesh values: device_put to a member device is a
        # local-shard fetch (no cross-device traffic)
        return jax.device_put(arr, ctx.jax_device)


def create(name: str = "local") -> KVStore:
    """Create a KVStore (reference: kvstore.cc factory ~L30)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    kv_type = name.lower()
    if kv_type in ("local", "local_allreduce_cpu", "local_allreduce_device",
                   "device", "nccl"):
        return KVStore("device" if kv_type != "local" else "local")
    if kv_type in ("dist_sync", "dist_sync_device", "dist_device_sync"):
        return KVStore(kv_type)
    if kv_type == "dist_async":
        raise MXNetError(
            "dist_async is not supported on TPU: asynchronous parameter "
            "serving has no SPMD analog (see SURVEY §2.3); use dist_sync")
    raise MXNetError(f"unknown KVStore type {name!r}")
