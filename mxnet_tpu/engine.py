"""Execution-engine facade.

Reference parity: src/engine/ (ThreadedEnginePerDevice / NaiveEngine,
MXNET_ENGINE_TYPE selection — engine.cc CreateEngine ~L40; WaitForVar /
WaitForAll — threaded_engine.cc ~L300).

On TPU the dependency engine's job — async dispatch, per-device streams,
read/write hazard ordering — is performed by PjRt: jax dispatches
asynchronously and orders operations on each device stream by construction,
and our NDArray mutation model (buffer swap, never in-place writes) removes
write hazards entirely.  What remains here:

  * ``NaiveEngine`` semantics: ``MXNET_ENGINE_TYPE=NaiveEngine`` makes every
    op synchronous (block_until_ready after dispatch) — the serial oracle the
    reference uses for race debugging (SURVEY §5.2).
  * ``wait_all`` / per-array ``wait_to_read`` barriers.
"""
from __future__ import annotations

import weakref

from .base import env_str

__all__ = ["is_naive", "set_engine_type", "track", "wait_all"]

_ENGINE_TYPE = env_str("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")

# Live arrays that may have outstanding async work; wait_all blocks on them.
_live: "weakref.WeakSet" = weakref.WeakSet()


def is_naive() -> bool:
    return _ENGINE_TYPE == "NaiveEngine"


def set_engine_type(name: str) -> None:
    global _ENGINE_TYPE
    _ENGINE_TYPE = name


def track(nd) -> None:
    """Register an NDArray for wait_all barriers."""
    _live.add(nd)


def wait_all() -> None:
    """Block until all outstanding device work is complete.

    Reference: MXNDArrayWaitAll -> Engine::WaitForAll.  Failed async
    computations surface HERE (it is the barrier users call to flush
    errors): every live array is drained, then the first failure is
    re-raised (r3 verdict: swallowing it dropped async errors silently).
    """
    first_err = None
    for nd in list(_live):
        try:
            nd.wait_to_read()
        except Exception as e:  # drain the rest before raising
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err
