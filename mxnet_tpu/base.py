"""Foundation utilities for mxnet_tpu.

TPU-native rebuild of the roles played by ``python/mxnet/base.py`` and
``3rdparty/dmlc-core`` in the reference (ctypes lib loading, error state,
dtype maps).  There is no C ABI here: the "library" below us is JAX/XLA, so
this module only holds dtype plumbing, env-var helpers and shared errors.

Reference parity: python/mxnet/base.py (~L100-300), dmlc parameter defaults.
"""
from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "integer_types",
    "shape_types",
    "dtype_np",
    "dtype_name",
    "env_int",
    "env_str",
    "env_bool",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: MXGetLastError surfaced errors)."""


string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)
shape_types = (tuple, list)

# MXNet 1.x dtype universe (reference: include/mxnet/base.h mshadow type switch).
# bfloat16 is promoted to a first-class citizen for TPU.
_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": None,  # resolved lazily via ml_dtypes to avoid jax import here
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def _bfloat16():
    import ml_dtypes  # shipped with jax

    return np.dtype(ml_dtypes.bfloat16)


_ML_FLOAT_DTYPES = None


def is_float_dtype(dtype: Any) -> bool:
    """True for any floating dtype INCLUDING ml_dtypes floats (bfloat16
    reports numpy kind 'V', so dtype.kind == 'f' checks are wrong — the
    BENCH_r02 crash class).  Single source of truth for this check.

    Matches the explicit ml_dtypes float set rather than all of kind 'V'
    (which would also claim int4/structured dtypes are floats)."""
    global _ML_FLOAT_DTYPES
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return True
    if _ML_FLOAT_DTYPES is None:
        import ml_dtypes

        found = set()
        for name in ("bfloat16", "float8_e4m3", "float8_e4m3fn",
                     "float8_e4m3b11_fnuz", "float8_e4m3fnuz",
                     "float8_e5m2", "float8_e5m2fnuz", "float8_e3m4",
                     "float8_e8m0fnu", "float4_e2m1fn", "float6_e2m3fn",
                     "float6_e3m2fn"):
            t = getattr(ml_dtypes, name, None)
            if t is not None:
                found.add(np.dtype(t))
        _ML_FLOAT_DTYPES = found
    return dt in _ML_FLOAT_DTYPES


def dtype_np(dtype: Any) -> np.dtype:
    """Normalize a user-supplied dtype (string, np.dtype, type) to np.dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return _bfloat16()
        if dtype in _DTYPE_ALIASES:
            return np.dtype(_DTYPE_ALIASES[dtype])
        return np.dtype(dtype)
    return np.dtype(dtype)


def dtype_name(dtype: Any) -> str:
    """Canonical string name for a dtype."""
    d = dtype_np(dtype)
    return d.name


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def env_bool(name: str, default: bool = False) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def canonical_kwargs(kwargs: dict) -> Tuple:
    """Hashable, order-independent key for an op's attribute dict.

    Used to key per-op jit caches (reference analog: op param struct hashing
    feeding CachedOp signatures, src/imperative/cached_op.cc ~L200).
    """
    items = []
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, list):
            v = tuple(v)
        elif isinstance(v, np.dtype):
            v = v.name
        elif isinstance(v, type):
            v = np.dtype(v).name
        items.append((k, v))
    return tuple(items)
