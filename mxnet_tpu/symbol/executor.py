"""Executor: bound symbolic graph.

Reference parity: python/mxnet/symbol/executor.py (Executor.forward/backward
~L100-300) over src/executor/graph_executor.cc (GraphExecutor::Init ~L300,
RunOps ~L1300) and the memory-planning passes.

TPU-native design: `bind` captures the argument arrays; `forward` runs ONE
jit-compiled function for the whole graph (XLA owns memory planning, fusion,
and scheduling — the reference's InitDataEntryMemory/PlanMemory/bulk-exec
work).  `backward` runs a second jitted function computing the vjp of the
whole graph w.r.t. the gradient-requiring arguments; like the reference's
backward pass it writes/accumulates into pre-allocated grad arrays
(grad_req write/add/null).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..base import MXNetError
from .symbol import Symbol, build_graph_eval

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol: Symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        from ..context import current_context
        from ..ndarray import NDArray

        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        self.arg_dict: Dict[str, NDArray] = _as_dict(args, arg_names, "args")
        self.aux_dict: Dict[str, NDArray] = _as_dict(aux_states, aux_names,
                                                     "aux_states")
        self.grad_req: Dict[str, str] = _req_dict(grad_req, arg_names)
        self.grad_dict: Dict[str, NDArray] = _as_dict(args_grad, arg_names,
                                                      "args_grad", partial=True)
        self.outputs: List[NDArray] = []
        self._fwd_cache: Dict[bool, Any] = {}
        self._bwd_fn = None
        self._last_train_feed = None
        self._tele_sigs: Dict[bool, Any] = {}

    _tele_counter = 0

    def _tele_name(self) -> str:
        """Telemetry key, stored ON the symbol: executors over the same
        symbol aggregate (the classic storm is a reshape/_simple_bind loop
        making a fresh executor per ragged batch), distinct symbols never
        collide, and — unlike keying by id() — a garbage-collected
        symbol's key can't be inherited by an unrelated new one."""
        name = getattr(self._symbol, "_tele_name", None)
        if name is None:
            Executor._tele_counter += 1
            name = (f"Executor:{getattr(self._symbol, 'name', None) or 'sym'}"
                    f"#{Executor._tele_counter}")
            try:
                self._symbol._tele_name = name
            except AttributeError:  # slots/frozen symbol: fall back
                pass
        return name

    # -- construction helpers ---------------------------------------------
    @classmethod
    def _simple_bind(cls, symbol: Symbol, ctx, grad_req, type_dict, shapes):
        from ..context import current_context
        from ..ndarray import zeros

        ctx = ctx or current_context()
        type_dict = type_dict or {}
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)

        args = {}
        for name, shp in zip(arg_names, arg_shapes):
            dtype = type_dict.get(name, "float32")
            args[name] = zeros(shp, ctx=ctx, dtype=dtype)
        aux = {}
        for name, shp in zip(aux_names, aux_shapes):
            aux[name] = zeros(shp, ctx=ctx, dtype=type_dict.get(name, "float32"))

        req = _req_dict(grad_req, arg_names)
        grads = {}
        for name in arg_names:
            if req.get(name, "null") != "null":
                grads[name] = zeros(args[name].shape, ctx=ctx,
                                    dtype=type_dict.get(name, "float32"))
        return cls(symbol, ctx=ctx, args=args, args_grad=grads,
                   grad_req=req, aux_states=aux)

    # -- forward -----------------------------------------------------------
    def forward(self, is_train: bool = False, **kwargs):
        from ..ndarray import NDArray, array

        self._monitor_ran = True  # mx.Monitor: this executor ran

        for name, val in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError(f"unknown argument {name!r}")
            if isinstance(val, NDArray):
                self.arg_dict[name]._set_data(val.copyto(self._ctx)._data)
            else:
                self.arg_dict[name]._set_data(
                    array(val, ctx=self._ctx)._data)

        feed = {name: a._data for name, a in self.arg_dict.items()}
        feed.update({name: a._data for name, a in self.aux_dict.items()})
        key = self._next_key()

        fwd = self._fwd_cache.get(is_train)
        was_cold = fwd is None
        if fwd is None:
            import jax

            fwd = jax.jit(build_graph_eval(self._symbol._entries, is_train))
            self._fwd_cache[is_train] = fwd

        # telemetry: the jit cache is keyed on the feed's shapes/dtypes —
        # a fresh signature means XLA recompiles this whole graph.  Keyed
        # by SYMBOL identity, not executor instance: the classic storm is
        # an Executor.reshape/_simple_bind loop that makes a fresh
        # executor per ragged batch over the same symbol, and those must
        # aggregate; distinct models (distinct symbols) must not.
        import time as _time

        from .. import telemetry

        tele_name = self._tele_name()
        if telemetry.retrace_enabled():
            # feed shapes/dtypes are FIXED at bind time (forward() writes
            # into pre-allocated arrays), so the signature is built once
            # per (executor, is_train) and reused — the steady-state probe
            # is a dict hit, not an O(n log n) walk of every param
            sig = self._tele_sigs.get(is_train)
            if sig is None:
                sig = (is_train,
                       tuple(sorted((n, tuple(a.shape), str(a.dtype))
                                    for n, a in feed.items())))
                self._tele_sigs[is_train] = sig
            # OR with was_cold: a SECOND executor over the same symbol
            # re-jits (and XLA recompiles) even though the symbol-keyed
            # registry has seen the signature — that compile must not be
            # booked as steady-state exec
            traced = telemetry.note_signature(tele_name, sig) or was_cold
        else:
            traced = was_cold
        t0 = _time.perf_counter()
        outs, aux_updates = fwd(feed, key)
        if telemetry.enabled():
            self._tele_steps = getattr(self, "_tele_steps", 0) + 1
            telemetry.record_step(tele_name, step=self._tele_steps,
                                  wall_s=_time.perf_counter() - t0,
                                  traced=traced)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        for name, val in aux_updates.items():
            self.aux_dict[name]._set_data(val)
        if is_train:
            self._last_train_feed = (feed, key)
        return self.outputs

    # -- backward ----------------------------------------------------------
    def backward(self, out_grads=None):
        from ..ndarray import NDArray

        if self._last_train_feed is None:
            raise MXNetError("backward called before forward(is_train=True)")
        feed, key = self._last_train_feed

        diff_names = sorted(
            name for name, req in self.grad_req.items()
            if req != "null" and name in self.arg_dict
            and np.dtype(self.arg_dict[name]._data.dtype).kind == "f")

        if self._bwd_fn is None:
            import jax

            entries = self._symbol._entries
            eval_fn = build_graph_eval(entries, True)
            names = tuple(diff_names)

            def bwd(diff_vals, const_vals, key, ograds):
                def f(dv):
                    full = dict(const_vals)
                    full.update(dict(zip(names, dv)))
                    outs, _ = eval_fn(full, key)
                    return outs

                _, vjp = jax.vjp(f, tuple(feedv for feedv in diff_vals))
                (grads,) = vjp(ograds)
                return grads

            self._bwd_fn = jax.jit(bwd)

        if out_grads is None:
            import jax.numpy as jnp

            ograds = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ograds = [g._data for g in out_grads]

        diff_vals = tuple(feed[n] for n in diff_names)
        const_vals = {k: v for k, v in feed.items() if k not in set(diff_names)}
        grads = self._bwd_fn(diff_vals, const_vals, key, list(ograds))

        for name, g in zip(diff_names, grads):
            tgt = self.grad_dict.get(name)
            if tgt is None:
                continue
            if self.grad_req[name] == "add":
                tgt._set_data(tgt._data + g)
            else:
                tgt._set_data(g)

    # -- accessors ---------------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, val in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(
                    val.copyto(self._ctx)._data.astype(
                        self.arg_dict[name]._data.dtype))
            elif not allow_extra_params:
                raise MXNetError(f"unknown arg param {name!r}")
        for name, val in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name]._set_data(val.copyto(self._ctx)._data)
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux param {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **shapes):
        """Rebind with new input shapes, carrying over current parameter and
        aux values whose shapes are unchanged (reference: Executor.reshape).
        jit recompiles per signature, so only the arrays are reallocated."""
        new = Executor._simple_bind(self._symbol, self._ctx,
                                    self.grad_req, None, shapes)
        for name, arr in self.arg_dict.items():
            tgt = new.arg_dict.get(name)
            if tgt is not None and tgt.shape == arr.shape:
                tgt._set_data(arr._data)
        for name, arr in self.aux_dict.items():
            tgt = new.aux_dict.get(name)
            if tgt is not None and tgt.shape == arr.shape:
                tgt._set_data(arr._data)
        return new

    def _next_key(self):
        from .. import random as _rng

        return _rng.next_key()


def _as_dict(values, names, what, partial=False):
    from ..ndarray import NDArray

    if values is None:
        return {}
    if isinstance(values, dict):
        for k in values:
            if k not in names:
                raise MXNetError(f"{what}: unknown name {k!r}")
        return dict(values)
    values = list(values)
    if not partial and len(values) != len(names):
        raise MXNetError(f"{what}: expected {len(names)} arrays "
                         f"({names}), got {len(values)}")
    out = {}
    for name, v in zip(names, values):
        if v is not None:
            if not isinstance(v, NDArray):
                raise MXNetError(f"{what}: {name} is not an NDArray")
            out[name] = v
    return out


def _req_dict(grad_req, arg_names):
    if isinstance(grad_req, str):
        return {n: grad_req for n in arg_names}
    if isinstance(grad_req, (list, tuple)):
        return dict(zip(arg_names, grad_req))
    if isinstance(grad_req, dict):
        return {n: grad_req.get(n, "null") for n in arg_names}
    raise MXNetError("grad_req must be str, list, or dict")
