"""Symbol: the declarative graph-construction API.

Reference parity: python/mxnet/symbol/symbol.py (class Symbol: composition,
infer_shape ~L1000, simple_bind ~L1500, tojson) over the nnvm graph IR
(3rdparty/tvm/nnvm include/nnvm/{node.h,graph.h,symbolic.h}).

TPU-native design: a Symbol is a lightweight python DAG over the same op
registry the imperative path uses (SURVEY.md invariant #2: one registry
serves both paths).  Binding a symbol does NOT build per-node executors the
way GraphExecutor does (src/executor/graph_executor.cc GraphExecutor::Init
~L300) — instead the whole graph is evaluated as one pure jax function and
jit-compiled into a single XLA executable, so memory planning, fusion, and
scheduling (the reference's PlanMemory / bulk-exec machinery) are XLA's job.
"""
from __future__ import annotations

import ast
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]

# ---------------------------------------------------------------------------
# graph nodes
# ---------------------------------------------------------------------------
_UID: Dict[str, int] = {}


def _auto_name(op_name: str) -> str:
    base = op_name.lstrip("_").lower()
    n = _UID.get(base, 0)
    _UID[base] = n + 1
    return f"{base}{n}"


class _Node:
    """One graph node: a variable (op is None) or an op application."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "vattrs")

    def __init__(self, op: Optional[str], name: str, attrs: dict,
                 inputs: List[Tuple["_Node", int]], num_outputs: int = 1,
                 vattrs: Optional[dict] = None):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.num_outputs = num_outputs
        self.vattrs = vattrs or {}   # variable decorations: shape/dtype/attr

    def is_variable(self) -> bool:
        return self.op is None


def _topo_order(entries: Sequence[Tuple[_Node, int]]) -> List[_Node]:
    """DFS post-order over inputs — matches the reference's list_arguments
    ordering (data before its consumers' weights, etc.)."""
    seen: Dict[int, bool] = {}
    order: List[_Node] = []

    def visit(node: _Node):
        if id(node) in seen:
            return
        seen[id(node)] = True
        for parent, _ in node.inputs:
            visit(parent)
        order.append(node)

    for node, _ in entries:
        visit(node)
    return order


# ---------------------------------------------------------------------------
# per-op symbolic metadata
# ---------------------------------------------------------------------------
# auxiliary-state argument names per op (reference: op's FMutateInputs —
# mutated inputs become aux states, e.g. BatchNorm moving stats)
_AUX_ARGS = {"BatchNorm": ("moving_mean", "moving_var")}

# ops whose registered fn takes an RNG key that the executor injects
_RNG_OPS = {"Dropout"}


def _op_arg_names(op_name: str) -> Tuple[List[str], Optional[str]]:
    """(required array-arg names, varargs name or None) from the registered
    fn signature; the RNG key parameter is never a graph input."""
    import inspect

    op = _reg.get_op(op_name)
    sig = inspect.signature(op.fn)
    req, var = [], None
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            var = p.name
        elif p.kind == p.POSITIONAL_OR_KEYWORD and p.default is p.empty:
            if p.name == "key" and op_name in _RNG_OPS:
                continue
            req.append(p.name)
    return req, var


def static_num_outputs(op_name: str, attrs: dict) -> int:
    """Build-time output multiplicity for ops whose count is known from
    attrs — lets ``sym.SliceChannel(x, num_outputs=3)[i]`` index outputs
    before any evaluation (reference: nnvm FNumOutputs)."""
    if op_name in ("SliceChannel", "split"):
        return int(attrs.get("num_outputs", 1))
    if op_name == "split_v2":
        sections = int(attrs.get("sections", 0) or 0)
        if sections > 0:
            return sections
        spec = attrs.get("indices_or_sections", 1)
        return int(spec) if isinstance(spec, int) else len(spec) + 1
    if op_name in ("moments", "linalg_slogdet", "linalg_gelqf"):
        return 2
    if op_name == "topk" and attrs.get("ret_typ") == "both":
        return 2
    if op_name in ("RNN", "_fused_rnn"):
        if op_name == "_fused_rnn" or attrs.get("state_outputs"):
            return 3 if attrs.get("mode", "lstm") == "lstm" else 2
    return 1


def _infer_param_shape(op_name: str, arg_name: str, data_shape, attrs):
    """Shape of an auto-created parameter variable given the op's data input
    shape — the symbolic twin of Gluon deferred init (reference: per-op
    FInferShape back-propagating unknown arg shapes)."""
    a = attrs
    if op_name == "FullyConnected":
        nh = int(a["num_hidden"])
        if arg_name == "weight":
            flat = a.get("flatten", True)
            in_units = (int(np.prod(data_shape[1:])) if flat
                        else int(data_shape[-1]))
            return (nh, in_units)
        if arg_name == "bias":
            return (nh,)
    elif op_name in ("Convolution", "Deconvolution"):
        nf = int(a["num_filter"])
        ng = int(a.get("num_group", 1))
        kernel = tuple(int(k) for k in a["kernel"])
        c = int(data_shape[1])
        if arg_name == "weight":
            if op_name == "Convolution":
                return (nf, c // ng) + kernel
            return (c, nf // ng) + kernel
        if arg_name == "bias":
            return (nf,)
    elif op_name == "BatchNorm":
        axis = int(a.get("axis", 1))
        return (int(data_shape[axis]),)
    elif op_name == "LayerNorm":
        axis = int(a.get("axis", -1))
        return (int(data_shape[axis]),)
    elif op_name == "Embedding":
        if arg_name == "weight":
            return (int(a["input_dim"]), int(a["output_dim"]))
    elif op_name == "SoftmaxOutput":
        if arg_name == "label":
            if a.get("multi_output", False):
                return (data_shape[0],) + tuple(data_shape[2:])
            return tuple(data_shape[:-1])
    elif op_name in ("LinearRegressionOutput", "MAERegressionOutput",
                     "LogisticRegressionOutput"):
        if arg_name == "label":
            return tuple(data_shape)
    elif op_name == "RNN":
        if arg_name == "parameters":
            # packed flat vector size from the shared layout helper
            from ..ops.rnn_ops import rnn_packed_layout

            _, total = rnn_packed_layout(
                a.get("mode", "lstm"), int(data_shape[2]),
                int(a["state_size"]), int(a.get("num_layers", 1)),
                a.get("bidirectional", False))
            return (total,)
        if arg_name in ("state", "state_cell"):
            H = int(a["state_size"])
            L = int(a.get("num_layers", 1))
            dirs = 2 if a.get("bidirectional", False) else 1
            return (L * dirs, int(data_shape[1]), H)
    return None


# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------
class Symbol:
    """An immutable handle to one or more outputs of a graph node."""

    __slots__ = ("_entries",)

    def __init__(self, entries: Sequence[Tuple[_Node, int]]):
        self._entries = list(entries)

    # -- identity ----------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def attr(self, key):
        node = self._entries[0][0]
        v = node.attrs.get(key, node.vattrs.get("attr", {}).get(key))
        return None if v is None else str(v)

    def list_attr(self):
        node = self._entries[0][0]
        out = {k: str(v) for k, v in node.attrs.items()}
        out.update({k: str(v) for k, v in node.vattrs.get("attr", {}).items()})
        return out

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._entries)
        return f"<Symbol {names}>"

    def __iter__(self):
        return (Symbol([e]) for e in self._entries)

    def __len__(self):
        return len(self._entries)

    def __getitem__(self, index):
        if isinstance(index, str):
            for e in self._entries:
                if _entry_name(e) == index or e[0].name == index:
                    return Symbol([e])
            raise MXNetError(f"no output named {index!r}")
        return Symbol([self._entries[index]])

    # -- graph queries -----------------------------------------------------
    def list_arguments(self) -> List[str]:
        aux = set(self._aux_nodes())
        return [n.name for n in _topo_order(self._entries)
                if n.is_variable() and id(n) not in aux]

    def list_auxiliary_states(self) -> List[str]:
        order = {id(n): i for i, n in enumerate(_topo_order(self._entries))}
        nodes = self._aux_node_objs()
        nodes.sort(key=lambda n: order[id(n)])
        return [n.name for n in nodes]

    def list_outputs(self) -> List[str]:
        return [_entry_name(e) for e in self._entries]

    def list_inputs(self) -> List[str]:
        return self.list_arguments() + self.list_auxiliary_states()

    def _aux_node_objs(self) -> List[_Node]:
        out, seen = [], set()
        for node in _topo_order(self._entries):
            if node.op in _AUX_ARGS:
                req, _ = _op_arg_names(node.op)
                for aname in _AUX_ARGS[node.op]:
                    idx = req.index(aname)
                    parent = node.inputs[idx][0]
                    if parent.is_variable() and id(parent) not in seen:
                        seen.add(id(parent))
                        out.append(parent)
        return out

    def _aux_nodes(self):
        return [id(n) for n in self._aux_node_objs()]

    def get_internals(self) -> "Symbol":
        entries = []
        for node in _topo_order(self._entries):
            for i in range(node.num_outputs):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_s, out_s, aux_s = self._infer(partial=False, shapes=kwargs,
                                          pos_shapes=args)
        return arg_s, out_s, aux_s

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer(partial=True, shapes=kwargs, pos_shapes=args)

    def infer_type(self, **kwargs):
        structs = self._infer_structs(shapes={}, dtypes=kwargs, partial=True)
        args = self.list_arguments()
        auxs = self.list_auxiliary_states()
        def dt(name):
            s = structs["vars"].get(name)
            return None if s is None else np.dtype(s.dtype)
        return ([dt(a) for a in args],
                [None if s is None else np.dtype(s.dtype)
                 for s in structs["outs"]],
                [dt(a) for a in auxs])

    def _infer(self, partial, shapes, pos_shapes=()):
        args = self.list_arguments()
        if pos_shapes:
            shapes = dict(shapes)
            for name, shp in zip(args, pos_shapes):
                if shp is not None:
                    shapes[name] = shp
        structs = self._infer_structs(shapes=shapes, dtypes={}, partial=partial)
        auxs = self.list_auxiliary_states()
        def shp(name):
            s = structs["vars"].get(name)
            return None if s is None else tuple(s.shape)
        arg_shapes = [shp(a) for a in args]
        aux_shapes = [shp(a) for a in auxs]
        out_shapes = [None if s is None else tuple(s.shape)
                      for s in structs["outs"]]
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            missing = [a for a, s in zip(args, arg_shapes) if s is None]
            raise MXNetError(f"infer_shape incomplete; unknown args: {missing}"
                             f" (provide their shapes)")
        return arg_shapes, out_shapes, aux_shapes

    def _infer_structs(self, shapes: dict, dtypes: dict, partial: bool):
        """Topo walk computing jax.ShapeDtypeStruct per entry; unknown
        parameter variables get shapes from _infer_param_shape."""
        import jax

        order = _topo_order(self._entries)
        var_struct: Dict[str, Any] = {}
        node_out: Dict[int, list] = {}

        for node in order:
            if node.is_variable():
                shp = shapes.get(node.name, node.vattrs.get("shape"))
                dt = dtypes.get(node.name, node.vattrs.get("dtype")) or "float32"
                if shp is not None:
                    var_struct[node.name] = jax.ShapeDtypeStruct(
                        tuple(shp), np.dtype(dt))
                node_out[id(node)] = [var_struct.get(node.name)]
                continue

            req, _varargs = _op_arg_names(node.op)

            def _aname(i):
                return req[i] if i < len(req) else (_varargs or f"arg{i}")
            in_structs = []
            data_struct = None
            for i, (parent, oidx) in enumerate(node.inputs):
                s = node_out.get(id(parent), [None])[oidx] \
                    if not parent.is_variable() else var_struct.get(parent.name)
                if s is None and parent.is_variable() and data_struct is not None:
                    shp = _infer_param_shape(node.op, _aname(i),
                                             data_struct.shape, node.attrs)
                    if shp is not None:
                        s = jax.ShapeDtypeStruct(shp, np.dtype("float32"))
                        var_struct[parent.name] = s
                if i == 0:
                    data_struct = s
                in_structs.append(s)

            if any(s is None for s in in_structs):
                node_out[id(node)] = [None] * node.num_outputs
                continue
            try:
                outs = jax.eval_shape(
                    lambda *xs, _n=node: _apply_node(_n, list(xs), None, False),
                    *in_structs)
            except Exception as e:  # noqa: BLE001
                if partial:
                    node_out[id(node)] = [None] * node.num_outputs
                    continue
                raise MXNetError(
                    f"shape inference failed at node {node.name} "
                    f"(op {node.op}): {e}") from e
            outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
            node_out[id(node)] = outs
            node.num_outputs = len(outs)

        out_structs = []
        for node, idx in self._entries:
            lst = node_out.get(id(node), [None])
            out_structs.append(lst[idx] if idx < len(lst) else None)
        # "nodes": per-node output structs keyed by id(node) — consumers
        # like the ONNX exporter need intermediate shapes/dtypes, not just
        # the graph boundary
        return {"vars": var_struct, "outs": out_structs, "nodes": node_out}

    # -- graph passes ------------------------------------------------------
    def optimize_for(self, backend: str, args=None, aux=None, **kwargs):
        """Partition the graph for a subgraph backend (reference:
        sym.optimize_for / MXOptimizeForBackend over build_subgraph.cc)."""
        from ..subgraph import partition

        return partition(self, backend)

    def get_backend_symbol(self, backend: str):
        """1.x-era spelling of optimize_for (reference c_api)."""
        return self.optimize_for(backend)

    # -- binding / evaluation ---------------------------------------------
    def _env_partitioned(self):
        """Apply the MXNET_SUBGRAPH_BACKEND env hook before binding
        (reference: build_subgraph.cc env-dispatch)."""
        from ..subgraph import env_backend

        be = env_backend()
        return self.optimize_for(be) if be else self

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from .executor import Executor

        return Executor(self._env_partitioned(), ctx=ctx, args=args,
                        args_grad=args_grad, grad_req=grad_req,
                        aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **shapes):
        from .executor import Executor

        return Executor._simple_bind(self._env_partitioned(), ctx=ctx,
                                     grad_req=grad_req, type_dict=type_dict,
                                     shapes=shapes)

    def eval(self, ctx=None, **kwargs):
        from ..ndarray import NDArray

        arg_arrays = {k: v for k, v in kwargs.items()
                      if isinstance(v, NDArray)}
        exe = self.bind(ctx=ctx, args=arg_arrays, grad_req="null")
        return exe.forward(is_train=False)

    # -- serialization -----------------------------------------------------
    def tojson(self) -> str:
        order = _topo_order(self._entries)
        nid = {id(n): i for i, n in enumerate(order)}
        nodes, arg_nodes = [], []
        for i, n in enumerate(order):
            if n.is_variable():
                arg_nodes.append(i)
                entry = {"op": "null", "name": n.name, "inputs": []}
                vat = {}
                if n.vattrs.get("shape") is not None:
                    vat["__shape__"] = str(tuple(n.vattrs["shape"]))
                if n.vattrs.get("dtype") is not None:
                    vat["__dtype__"] = str(n.vattrs["dtype"])
                init = n.vattrs.get("init")
                if init is not None:
                    # reference format: '["name", {kwargs}]' (__init__ attr)
                    vat["__init__"] = (init if isinstance(init, str)
                                       else init.dumps())
                if vat:
                    entry["attrs"] = vat
            else:
                entry = {
                    "op": n.op, "name": n.name,
                    "attrs": {k: str(v) for k, v in n.attrs.items()},
                    "inputs": [[nid[id(p)], oi, 0] for p, oi in n.inputs],
                }
            nodes.append(entry)
        heads = [[nid[id(n)], oi, 0] for n, oi in self._entries]
        return json.dumps({
            "nodes": nodes, "arg_nodes": arg_nodes, "heads": heads,
            "attrs": {"mxnet_version": ["int", 10500]},
        }, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- composition sugar -------------------------------------------------
    def __call__(self, **kwargs):
        """Compose: replace named variable inputs with other symbols."""
        mapping = {}
        for name, s in kwargs.items():
            if not isinstance(s, Symbol):
                raise MXNetError("compose expects Symbol keyword arguments")
            mapping[name] = s._entries[0]
        memo: Dict[int, _Node] = {}  # shared across heads to keep the DAG
        return Symbol([_substitute(e, mapping, memo) for e in self._entries])

    # -- arithmetic sugar --------------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _apply_sym(op, [a, b], {})
        return _apply_sym(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, o): return self._binary(o, "broadcast_add", "_plus_scalar")
    def __radd__(self, o): return self.__add__(o)
    def __sub__(self, o): return self._binary(o, "broadcast_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binary(o, "broadcast_sub", "_rminus_scalar", True)
    def __mul__(self, o): return self._binary(o, "broadcast_mul", "_mul_scalar")
    def __rmul__(self, o): return self.__mul__(o)
    def __truediv__(self, o): return self._binary(o, "broadcast_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binary(o, "broadcast_div", "_rdiv_scalar", True)
    def __pow__(self, o): return self._binary(o, "broadcast_power", "_power_scalar")
    def __neg__(self): return _apply_sym("_mul_scalar", [self], {"scalar": -1.0})

    # common method forms
    def reshape(self, *shape):
        # both spellings, like NDArray.reshape: s.reshape((a, b)) and
        # s.reshape(a, b) — hybrid_forward code uses either
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _apply_sym("Reshape", [self], {"shape": tuple(shape)})
    def transpose(self, axes=()): return _apply_sym("transpose", [self], {"axes": tuple(axes)})
    def astype(self, dtype): return _apply_sym("Cast", [self], {"dtype": str(np.dtype(dtype))})
    def expand_dims(self, axis):
        return _apply_sym("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _apply_sym("squeeze", [self],
                          {} if axis is None else {"axis": axis})

    def flatten(self):
        return _apply_sym("Flatten", [self], {})

    def sum(self, axis=None, keepdims=False):
        return _apply_sym("sum", [self], {"axis": axis, "keepdims": keepdims})
    def mean(self, axis=None, keepdims=False):
        return _apply_sym("mean", [self], {"axis": axis, "keepdims": keepdims})


def _entry_name(entry) -> str:
    node, idx = entry
    if node.is_variable():
        return node.name
    suffix = "_output" if node.num_outputs == 1 else f"_output{idx}"
    return node.name + suffix


def _substitute(entry, mapping, memo):
    node, idx = entry
    if id(node) in memo:
        return (memo[id(node)], idx)
    if node.is_variable():
        if node.name in mapping:
            return mapping[node.name]
        return entry
    new_inputs = [_substitute(e, mapping, memo) for e in node.inputs]
    new_node = _Node(node.op, node.name, node.attrs, new_inputs,
                     node.num_outputs)
    memo[id(node)] = new_node
    return (new_node, idx)


# ---------------------------------------------------------------------------
# node application / evaluation
# ---------------------------------------------------------------------------
def _apply_sym(op_name: str, inputs: List[Symbol], attrs: dict,
               name: Optional[str] = None) -> Symbol:
    _reg.get_op(op_name)  # validate
    name = name or _auto_name(op_name)
    entries = [s._entries[0] for s in inputs]
    node = _Node(op_name, name, attrs, entries)
    return Symbol([(node, 0)])


def _apply_node(node: _Node, in_vals: list, key, training: bool):
    """Execute one graph node on jax values (used by eval_shape and the
    executor's jitted whole-graph function)."""
    if node.op == "_subgraph":
        # a region claimed by a subgraph backend (mxnet_tpu.subgraph):
        # executes as one callable (jitted per-region under the executor's
        # outer jit this is a no-op; eagerly it is its own XLA program)
        return node.attrs["fn"](*in_vals)
    op = _reg.get_op(node.op)
    attrs = dict(node.attrs)
    if node.op == "Dropout":
        import jax

        if key is None or not training:
            attrs["training"] = False
            k = np.zeros((2,), np.uint32)
        else:
            attrs["training"] = True
            k = jax.random.fold_in(key, _stable_uid(node))
        return op.fn(in_vals[0], k, **attrs)
    if node.op == "BatchNorm":
        attrs["training"] = training
        attrs["output_mean_var"] = True
        out, mean, var = op.fn(*in_vals, **attrs)
        return out, mean, var
    return op.fn(*in_vals, **attrs)


_NODE_UIDS: Dict[int, int] = {}


def _stable_uid(node: _Node) -> int:
    uid = _NODE_UIDS.get(id(node))
    if uid is None:
        uid = len(_NODE_UIDS) + 1
        _NODE_UIDS[id(node)] = uid
    return uid


def build_graph_eval(entries: Sequence[Tuple[_Node, int]], training: bool):
    """Build fn(var_values: dict, key) -> (outputs: list, aux_updates: dict)
    evaluating the whole graph — this is the CachedOp/GraphExecutor
    equivalent: one pure function, one XLA executable after jit."""
    order = _topo_order(entries)

    def eval_fn(var_values: Dict[str, Any], key):
        vals: Dict[int, list] = {}
        aux_updates: Dict[str, Any] = {}
        for node in order:
            if node.is_variable():
                vals[id(node)] = [var_values[node.name]]
                continue
            ins = [vals[id(p)][oi] for p, oi in node.inputs]
            out = _apply_node(node, ins, key, training)
            if node.op == "BatchNorm":
                out, mean, var = out
                if training and not node.attrs.get("use_global_stats", False):
                    mom = float(node.attrs.get("momentum", 0.9))
                    req, _ = _op_arg_names("BatchNorm")
                    for stat, aname in ((mean, "moving_mean"),
                                        (var, "moving_var")):
                        parent = node.inputs[req.index(aname)][0]
                        if parent.is_variable():
                            old = var_values[parent.name]
                            aux_updates[parent.name] = (
                                mom * old + (1.0 - mom) * stat.astype(old.dtype))
                out = [out]
            elif not isinstance(out, (tuple, list)):
                out = [out]
            else:
                out = list(out)
            vals[id(node)] = out
            node.num_outputs = len(out)
        outs = [vals[id(n)][oi] for n, oi in entries]
        return outs, aux_updates

    return eval_fn


# ---------------------------------------------------------------------------
# public constructors
# ---------------------------------------------------------------------------
def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs) -> Symbol:
    if not isinstance(name, str):
        raise MXNetError("Variable name must be a string")
    from .. import attribute as _attribute

    # AttrScope attrs apply to variables created in the scope (reference
    # attribute.py); explicit attr wins.  __lr_mult__/__wd_mult__ scope
    # attrs map onto the typed fields when not given explicitly.
    attr = _attribute.current().get(attr)
    if lr_mult is None and "__lr_mult__" in attr:
        lr_mult = float(attr["__lr_mult__"])
    if wd_mult is None and "__wd_mult__" in attr:
        wd_mult = float(attr["__wd_mult__"])
    vattrs = {"shape": None if shape is None else tuple(shape),
              "dtype": dtype, "attr": dict(attr or {}), "init": init,
              "lr_mult": lr_mult, "wd_mult": wd_mult}
    node = _Node(None, name, {}, [], vattrs=vattrs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str: str) -> Symbol:
    data = json.loads(json_str)
    nodes_data = data["nodes"]
    built: List[_Node] = []
    for nd_ in nodes_data:
        if nd_["op"] == "null":
            vattrs = {}
            raw = nd_.get("attrs", {})
            if "__shape__" in raw:
                vattrs["shape"] = tuple(ast.literal_eval(raw["__shape__"]))
            if "__dtype__" in raw:
                vattrs["dtype"] = raw["__dtype__"]
            if "__init__" in raw:
                from .. import initializer as _init

                vattrs["init"] = _init.create(raw["__init__"])
            built.append(_Node(None, nd_["name"], {}, [], vattrs=vattrs))
        else:
            attrs = {k: _parse_attr(v)
                     for k, v in nd_.get("attrs", {}).items()}
            inputs = [(built[i], oi) for i, oi, *_ in nd_["inputs"]]
            node = _Node(nd_["op"], nd_["name"], attrs, inputs)
            node.num_outputs = static_num_outputs(nd_["op"], attrs)
            built.append(node)
    heads = data.get("heads", [[len(built) - 1, 0, 0]])
    return Symbol([(built[i], oi) for i, oi, *_ in heads])


def _parse_attr(v: str):
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v
