"""The ``mx.sym`` namespace.

Reference parity: python/mxnet/symbol/ — like ``mx.nd``, the op namespace is
generated from the registry at import time (symbol/register.py ~L100), so
every registered operator is available in both the imperative and the
symbolic spelling (SURVEY.md invariant #2).
"""
from __future__ import annotations

import inspect

from ..base import MXNetError
from ..ops import registry as _reg
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     _apply_sym, _auto_name, _Node, _op_arg_names, _AUX_ARGS,
                     static_num_outputs)
from .executor import Executor

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "Executor"]


def _make_sym_stub(op):
    req_names, varargs = _op_arg_names(op.name)
    sig = inspect.signature(op.fn)
    kw_order = [p.name for p in sig.parameters.values()
                if p.default is not p.empty]
    kw_ok = set(kw_order)
    no_bias_default = False
    if "no_bias" in sig.parameters:
        no_bias_default = bool(sig.parameters["no_bias"].default)

    def stub(*args, **kwargs):
        from .. import name as _nm

        explicit = kwargs.pop("name", None)
        name = (explicit if explicit is not None
                else _nm.current().get(None, _auto_name(op.name)))
        kwargs.pop("attr", None)
        sym_inputs = []
        # positional symbols fill required slots, then varargs
        pos = [a for a in args if isinstance(a, Symbol)]
        attrs_pos = [a for a in args if not isinstance(a, Symbol)]
        # keyword symbols by arg name
        by_name = {}
        for k in list(kwargs):
            if isinstance(kwargs[k], Symbol):
                by_name[k] = kwargs.pop(k)
        if varargs and not req_names:
            # fully-variadic op (Concat, add_n, UpSampling): all positional
            # symbols are inputs
            sym_inputs = pos
            pos = []
        else:
            for i, aname in enumerate(req_names):
                if aname in by_name:
                    sym_inputs.append(by_name.pop(aname))
                elif pos:
                    sym_inputs.append(pos.pop(0))
                else:
                    # auto-create variable (reference: symbolic auto args);
                    # aux-state args keep their canonical suffix
                    sym_inputs.append(Variable(f"{name}_{aname}"))
            if varargs and not kwargs.get("no_bias", no_bias_default):
                if by_name.get(varargs) is not None:
                    sym_inputs.append(by_name.pop(varargs))
                elif pos:
                    sym_inputs.extend(pos)
                    pos = []
                elif varargs == "bias":
                    sym_inputs.append(Variable(f"{name}_bias"))
        if pos:
            raise MXNetError(
                f"{op.name}: {len(pos)} unused positional symbol input(s)")
        if by_name:
            raise MXNetError(f"{op.name}: unknown symbol kwargs "
                             f"{sorted(by_name)}")
        # leftover positional scalars map onto keyword attrs in order
        if attrs_pos:
            free = [k for k in kw_order if k not in kwargs]
            for a, k in zip(attrs_pos, free):
                kwargs[k] = a
        bad = set(kwargs) - kw_ok
        if bad:
            raise MXNetError(f"{op.name}: unknown attrs {sorted(bad)}")
        entries = [s._entries[0] for s in sym_inputs]
        node = _Node(op.name, name, kwargs, entries)
        # AttrScope string attrs attach to op nodes too (introspection /
        # serialization metadata; op semantics come from kwargs)
        from .. import attribute as _attribute

        scoped = _attribute.current().get(None)
        if scoped:
            node.vattrs = {"attr": scoped}
        n_out = static_num_outputs(op.name, kwargs)
        node.num_outputs = n_out
        return Symbol([(node, i) for i in range(n_out)])

    stub.__name__ = op.name
    stub.__doc__ = op.__doc__
    return stub


_SKIP_PREFIXES = ("_random_", "_sample_", "sample_")


def _make_sym_ufunc(name, bop, np_fn, sop, rsop):
    """Symbol-side ufunc with scalar dispatch (reference: symbol.py
    _ufunc_helper — same table as the nd namespace)."""

    def f(lhs, rhs, **kw):
        g = globals()
        l, r = isinstance(lhs, Symbol), isinstance(rhs, Symbol)
        if l and r:
            return g[bop](lhs, rhs, **kw)
        if l:
            return g[sop](lhs, scalar=float(rhs), **kw)
        if r:
            return g[rsop](rhs, scalar=float(lhs), **kw)
        return np_fn(lhs, rhs)

    f.__name__ = name
    f.__doc__ = f"Element-wise {name} (maps to {bop} / {sop})."
    return f


def _populate():
    g = globals()
    for opname in _reg.list_ops():
        if opname.startswith(_SKIP_PREFIXES):
            continue
        op = _reg.get_op(opname)
        g[opname] = _make_sym_stub(op)
        __all__.append(opname)
    g["concat"] = g["Concat"]
    g["flatten"] = g["Flatten"]
    g["cast"] = g["Cast"]
    from ..ndarray import _UFUNCS

    for _name, (_bop, _np_fn, _sop, _rsop) in _UFUNCS.items():
        g[_name] = _make_sym_ufunc(_name, _bop, _np_fn, _sop, _rsop)
        __all__.append(_name)

    import numpy as _np

    g["power"] = _make_sym_ufunc("power", "broadcast_power", _np.power,
                                 "_power_scalar", "_rpower_scalar")
    __all__.append("power")

    # sym.linalg namespace: short spellings over the linalg_* stubs
    # (reference: python/mxnet/symbol/linalg.py).  Registered in
    # sys.modules so `import mxnet_tpu.symbol.linalg` works too.
    import sys
    import types

    lin = types.ModuleType(__name__ + ".linalg")
    lin.__doc__ = "The mx.sym.linalg namespace (linalg_* op spellings)."
    for opname in _reg.list_ops():
        if opname.startswith("linalg_"):
            setattr(lin, opname[len("linalg_"):], g[opname])
    g["linalg"] = lin
    sys.modules[lin.__name__] = lin


_populate()

from . import contrib  # noqa: E402,F401  (after stub autogen)
