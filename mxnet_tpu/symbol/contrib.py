"""The ``mx.sym.contrib`` namespace: short spellings of ``_contrib_*`` ops
(reference: python/mxnet/symbol/contrib.py)."""
from __future__ import annotations

from ..ops import registry as _reg

__all__ = []


def _populate():
    from .. import symbol as _sym_mod  # its op stubs exist by import order

    g = globals()
    for name in _reg.list_ops():
        if name.startswith("_contrib_") and hasattr(_sym_mod, name):
            short = name[len("_contrib_"):]
            g[short] = getattr(_sym_mod, name)
            __all__.append(short)


_populate()
